"""Multi-fidelity search engine — ASHA/BOHB rungs as a scheduler citizen.

Upstream Katib ships successive halving only as a stateless suggestion
service (suggest/hyperband.py reproduces it exactly): every rung restarts
survivors FROM SCRATCH with a bigger budget parameter, so the
device-seconds spent on the lower rung are thrown away. This module makes
the halving native by reusing machinery the repo already owns:

- **Rungs are fidelity levels over the budget knob** (``resource_name``
  algorithm setting — epochs/examples, classified as a *host* parameter by
  the semantic analyzer), so rung changes never recompile: every rung of a
  sweep shares one dispatch-group key (analysis/program.py ignores
  host-only differences) and therefore one AOT-warmed executable in the
  compile service.
- **A rung boundary is a completion, not a restart**: a trial launched
  with ``resource=r_k`` trains to r_k (resuming its own checkpoint from
  the previous rung through the ordinary ``ctx.checkpoint_store()`` path),
  reports its objective, and is *paused* — a non-victim variant of
  checkpoint-preemption: terminal-looking (EarlyStopped/``RungPaused``) so
  it frees its parallel slot and its devices, but with the observation log
  and checkpoint intact.
- **Promotion is the PBT exploit move across fidelities**: the SAME trial
  is resubmitted with the budget knob raised to r_{k+1} and its checkpoint
  directory re-attached, so the resumed stint continues the same PRNG
  stream and observation log — the PR 2 resume-bit-identical guarantees
  apply unchanged. Non-promoted trials finalize as early-stopped
  (``RungPruned``) with their observations intact.
- **Low-fidelity rungs pack**: same-rung trials share the budget value, so
  pack formation (controller/packing.py keys open packs by the rung's
  budget) can run a whole bottom rung as one vmapped program.
- **Promotions pack too** (ISSUE 13): with
  ``runtime.promotion_dwell_seconds > 0`` same-ladder promotion decisions
  accumulate for a short dwell window and are resubmitted under ONE
  dispatch barrier, so ``plan_packs`` forms vmapped packs at rung 1+
  instead of dispatching each promotion solo. A drain rule flushes the
  buffer the moment nothing is running, so the last stragglers never wait
  out the window. 0 (the default) submits at the decision point,
  byte-identical to the PR 11 behavior.

The promotion rule is asynchronous successive halving (Li et al., ASHA): a
paused trial at rung k is promotable when it ranks in the top
``floor(|rung_k| / eta)`` of every objective recorded at rung k. Decisions
are made at each boundary (scheduler worker thread) and re-checked on
every reconcile (:meth:`MultiFidelityEngine.pump`), which also prunes the
ladder once the sweep drains.

Two algorithms ride the engine (``ENGINE_ALGORITHMS``): ``asha`` (uniform
bottom-rung sampling, PR 11) and ``bohb`` (model-based bottom-rung
sampling — suggest/bohb.py fits a per-rung TPE/KDE over the fold index).
Both support **multi-bracket Hyperband** scheduling: the ``brackets``
algorithm setting builds several ladders with staggered ``min_resource``
(bracket b starts at base rung b) that share one experiment and one
admission budget; the suggester assigns new configurations round-robin by
remaining per-bracket budget (:func:`assign_brackets`), and every bracket
rides the same pause/promote/prune machinery below. The budget knob being
a host param, all brackets still share the single AOT-warmed executable.

Gating: the engine exists only when ``runtime.multifidelity`` is on AND an
experiment declares ``algorithm: asha`` or ``algorithm: bohb``. Hyperband
specs never touch it — the legacy stateless path is preserved
byte-identically.
"""

from __future__ import annotations

import logging
import math
import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..api.spec import ExperimentSpec, ObjectiveType, ParameterType
from ..api.status import Experiment, Trial, TrialCondition
from ..db.store import ObservationStore, objective_value
from ..earlystop.curves import ObjectiveCurveReader

log = logging.getLogger("katib_tpu.multifidelity")

ALGORITHM_NAME = "asha"
BOHB_ALGORITHM_NAME = "bohb"
# algorithms owned by the engine: both enter every configuration at a
# bracket's bottom rung and ride the pause/promote/prune machinery
ENGINE_ALGORITHMS = frozenset({ALGORITHM_NAME, BOHB_ALGORITHM_NAME})

# Persisted trial labels: the offline `katib-tpu rungs` view and the
# restart rebuild read them back from the state store.
RUNG_LABEL = "katib-tpu/rung"            # current rung index of the trial
PAUSED_LABEL = "katib-tpu/rung-paused"   # present while rung-paused (value: rung)
BRACKET_LABEL = "katib-tpu/bracket"      # hyperband bracket id (absent = 0)

DEFAULT_ETA = 3


@dataclass
class FidelityLadder:
    """The rung ladder of one bracket: budgets r_0 < r_1 < ... < r_top
    over the spec's ``resource_name`` parameter, geometric in ``eta`` and
    clipped to ``max_resource``."""

    resource_name: str
    eta: int
    rungs: List[float]
    integer: bool  # INT resource: budgets truncate like hyperband's

    @classmethod
    def from_spec(cls, spec: ExperimentSpec) -> "FidelityLadder":
        """Build the base (bracket-0) ladder from algorithm settings; raises
        ValueError on a malformed spec (the suggester's
        validate_algorithm_settings surfaces it)."""
        settings = spec.algorithm.settings_dict()
        resource = settings.get("resource_name", "")
        if not resource:
            raise ValueError(
                f"{spec.algorithm.algorithm_name or 'asha'} requires the "
                "resource_name setting"
            )
        param = next((p for p in spec.parameters if p.name == resource), None)
        if param is None:
            raise ValueError(
                f"resource_name {resource!r} must name an experiment parameter"
            )
        if param.parameter_type not in (ParameterType.INT, ParameterType.DOUBLE):
            raise ValueError(
                f"resource parameter {resource!r} must be int or double"
            )
        eta = int(float(settings.get("eta", DEFAULT_ETA)))
        if eta <= 1:
            raise ValueError("eta must be an integer greater than 1")
        fs = param.feasible_space
        lo_default = fs.min if fs.min not in (None, "") else "1"
        hi_default = fs.max if fs.max not in (None, "") else "0"
        min_r = float(settings.get("min_resource", lo_default))
        max_r = float(settings.get("max_resource", hi_default))
        if min_r <= 0:
            raise ValueError("min_resource must be positive")
        if max_r <= min_r:
            raise ValueError(
                f"max_resource ({max_r:g}) must exceed min_resource ({min_r:g})"
            )
        rungs = [min_r]
        while rungs[-1] < max_r:
            rungs.append(min(rungs[-1] * eta, max_r))
        integer = param.parameter_type == ParameterType.INT
        if integer:
            # dedupe after truncation (e.g. min=1, eta=2, max=3 -> 1,2,3)
            seen: List[float] = []
            for r in rungs:
                if not seen or int(r) != int(seen[-1]):
                    seen.append(float(int(r)))
            rungs = seen
        return cls(resource_name=resource, eta=eta, rungs=rungs, integer=integer)

    @property
    def top(self) -> int:
        return len(self.rungs) - 1

    def format(self, r: float) -> str:
        """Budget as the string assigned to the resource parameter (INT
        resources truncate, matching hyperband's _format_budget)."""
        return str(int(r)) if self.integer else repr(float(r))

    def rung_of(self, value: str) -> int:
        """Rung index of a budget assignment: the highest rung whose budget
        does not exceed the value (exact for engine-issued budgets; a
        tolerant floor for hand-written ones)."""
        v = float(value)
        idx = 0
        for i, r in enumerate(self.rungs):
            if v >= r - 1e-9:
                idx = i
        return idx


# -- multi-bracket geometry ----------------------------------------------------


def bracket_count(spec: ExperimentSpec) -> int:
    """The ``brackets`` algorithm setting (default 1). Validation lives in
    the suggester; consumers clamp defensively."""
    raw = spec.algorithm.settings_dict().get("brackets", "1")
    try:
        return max(int(float(raw)), 1)
    except ValueError:
        return 1


def bracket_ladders(spec: ExperimentSpec) -> List[FidelityLadder]:
    """One FidelityLadder per bracket, staggered min_resource: bracket b's
    ladder is the base ladder's rungs[b:], so its bottom rung IS base rung
    b — budgets stay the shared geometric points, and same-budget trials of
    different brackets still share one compiled program. The count is
    clamped so every bracket keeps at least two rungs."""
    base = FidelityLadder.from_spec(spec)
    b = min(bracket_count(spec), max(len(base.rungs) - 1, 1))
    return [
        FidelityLadder(
            resource_name=base.resource_name,
            eta=base.eta,
            rungs=list(base.rungs[i:]),
            integer=base.integer,
        )
        for i in range(b)
    ]


def bracket_quotas(max_trials: int, ladders: Sequence[FidelityLadder]) -> List[int]:
    """Admission split of ``maxTrialCount`` across brackets, Hyperband
    style: bracket b with s_b = top halvings weighs eta^{s_b} / (s_b + 1)
    — the cheap deep-halving bracket admits the most configurations.
    Largest-remainder rounding; every bracket gets at least one admission
    while the budget allows."""
    b = len(ladders)
    if b == 1:
        return [max_trials]
    weights = [
        (ladder.eta ** ladder.top) / (ladder.top + 1) for ladder in ladders
    ]
    total = sum(weights)
    raw = [max_trials * w / total for w in weights]
    counts = [int(r) for r in raw]
    rem = max_trials - sum(counts)
    order = sorted(range(b), key=lambda i: (-(raw[i] - counts[i]), i))
    for i in order[:rem]:
        counts[i] += 1
    for i in range(b):
        if counts[i] == 0:
            donor = counts.index(max(counts))
            if counts[donor] > 1:
                counts[donor] -= 1
                counts[i] += 1
    return counts


def assign_brackets(
    spec: ExperimentSpec,
    trials: Sequence[Trial],
    ladders: Sequence[FidelityLadder],
    n: int,
) -> List[int]:
    """Bracket id for each of ``n`` new admissions: round-robin by
    remaining per-bracket budget (quota minus already-admitted, counted
    from persisted bracket labels), ties to the lower bracket id. With one
    bracket this is a constant-0 list and the caller skips labeling."""
    if len(ladders) == 1:
        return [0] * n
    quotas = bracket_quotas(spec.max_trial_count or n, ladders)
    admitted: Dict[int, int] = {}
    for t in trials:
        b = _bracket_of(t, len(ladders))
        admitted[b] = admitted.get(b, 0) + 1
    out: List[int] = []
    for _ in range(n):
        b = max(
            range(len(ladders)),
            key=lambda i: (quotas[i] - admitted.get(i, 0), -i),
        )
        out.append(b)
        admitted[b] = admitted.get(b, 0) + 1
    return out


def _bracket_of(trial: Trial, n_brackets: int) -> int:
    try:
        b = int(trial.labels.get(BRACKET_LABEL, "0"))
    except ValueError:
        b = 0
    return min(max(b, 0), n_brackets - 1)


class _BracketRungs:
    """Rung tables of one bracket. Not self-locking: the engine's lock
    guards every mutation (caller holds it)."""

    def __init__(self, ladder: FidelityLadder):
        self.ladder = ladder
        # rung index -> {trial name: objective recorded at that boundary}
        self.scores: List[Dict[str, float]] = [dict() for _ in ladder.rungs]
        # rung index -> trials promoted OUT of that rung
        self.promoted: List[set] = [set() for _ in ladder.rungs]


class _ExperimentRungs:
    """Per-experiment state: one _BracketRungs per bracket plus the shared
    paused map. Caller holds the engine lock for every mutation."""

    def __init__(self, ladders: Sequence[FidelityLadder], maximize: bool):
        self.brackets = [_BracketRungs(ladder) for ladder in ladders]
        self.maximize = maximize
        self.paused: Dict[str, Tuple[int, int]] = {}  # name -> (bracket, rung)
        self.done = False


class MultiFidelityEngine:
    """Scheduler-citizen ASHA/BOHB: owns rung records, pause/promote/prune
    per bracket, and the dwell-window promotion buffer.

    Thread model: :meth:`on_rung_boundary` runs on scheduler worker
    threads, :meth:`pump` on the reconcile thread, dwell flushes on either
    plus a wake timer. The engine lock guards its tables only — it is
    never held across scheduler calls (submit / _record_terminal), so the
    only cross-subsystem lock edge is engine -> scheduler."""

    def __init__(
        self,
        state,
        obs_store: ObservationStore,
        events=None,
        metrics=None,
        dwell_seconds: float = 0.0,
        journal=None,
    ):
        self.state = state
        self.obs_store = obs_store
        self.events = events
        self.metrics = metrics
        # recovery journal (controller/recovery.py): promotion batches are
        # journaled before resubmission so the controller-kill chaos grammar
        # has a deterministic kill point at the promotion seam; None = off
        self.journal = journal
        self.dwell_seconds = max(float(dwell_seconds or 0.0), 0.0)
        self._lock = threading.Lock()
        self._exps: Dict[str, _ExperimentRungs] = {}
        # dwell buffer: experiment -> [(enqueued_at, name, bracket, rung)]
        self._pending: Dict[str, List[Tuple[float, str, int, int]]] = {}
        self._timers: Dict[str, threading.Timer] = {}

    # -- applicability -------------------------------------------------------

    @staticmethod
    def applies(spec: ExperimentSpec) -> bool:
        return spec.algorithm.algorithm_name in ENGINE_ALGORITHMS

    def _entry(self, exp: Experiment) -> _ExperimentRungs:
        """Get-or-build the experiment's rung tables, rebuilding from
        persisted trial labels + the fold index after a controller restart.
        Must be called WITHOUT the engine lock held (reads the store)."""
        with self._lock:
            st = self._exps.get(exp.name)
        if st is not None:
            return st
        ladders = bracket_ladders(exp.spec)
        maximize = exp.spec.objective.type == ObjectiveType.MAXIMIZE
        st = _ExperimentRungs(ladders, maximize)
        reader = ObjectiveCurveReader(self.obs_store, exp.spec.objective)
        for t in self.state.list_trials(exp.name):
            rung_lbl = t.labels.get(RUNG_LABEL)
            if rung_lbl is None:
                continue
            try:
                k = int(rung_lbl)
            except ValueError:
                continue
            b = _bracket_of(t, len(st.brackets))
            br = st.brackets[b]
            k = min(max(k, 0), br.ladder.top)
            score = reader.boundary_value(t.name)
            if (
                PAUSED_LABEL in t.labels
                and t.condition == TrialCondition.EARLY_STOPPED
                and score is not None
            ):
                br.scores[k][t.name] = score
                st.paused[t.name] = (b, k)
            else:
                # a trial past its bracket's rung 0 was promoted through
                # every lower rung; its per-rung boundary scores are gone,
                # so the rebuild backfills the current folded objective —
                # enough to keep rung sizes and promotion counts consistent
                for j in range(k):
                    if score is not None:
                        br.scores[j].setdefault(t.name, score)
                    br.promoted[j].add(t.name)
                if score is not None and (
                    t.condition == TrialCondition.EARLY_STOPPED or k == br.ladder.top
                ):
                    br.scores[k].setdefault(t.name, score)
        with self._lock:
            return self._exps.setdefault(exp.name, st)

    # -- rung boundary (scheduler worker thread) -----------------------------

    def on_rung_boundary(self, exp: Experiment, trial: Trial, observation, scheduler) -> bool:
        """Consulted by the scheduler when a trial COMPLETED its assigned
        budget. Returns True when the trial was paused at a rung boundary
        (the scheduler then skips normal finalization); False hands the
        trial back to the ordinary Succeeded path (non-engine experiment,
        top-of-ladder completion, or no usable objective)."""
        spec = exp.spec
        if not self.applies(spec):
            return False
        try:
            st = self._entry(exp)
        except Exception:
            log.debug("rung table unavailable for %s", exp.name, exc_info=True)
            return False
        b = _bracket_of(trial, len(st.brackets))
        ladder = st.brackets[b].ladder
        value = trial.assignments_dict().get(ladder.resource_name)
        if value is None:
            return False
        try:
            k = ladder.rung_of(value)
        except ValueError:
            return False
        score = objective_value(observation, spec.objective)
        if score is None or math.isnan(score):
            return False  # MetricsUnavailable classification handles it
        with self._lock:
            if st.done:
                return False
            st.brackets[b].scores[k][trial.name] = score
            if k >= ladder.top:
                # final fidelity: record for the rung view, finalize normally
                st.paused.pop(trial.name, None)
            else:
                st.paused[trial.name] = (b, k)
        self._note_bracket_gauge(exp.name, st)
        if k >= ladder.top:
            trial.labels[RUNG_LABEL] = str(k)
            return False
        # Pause: the non-victim variant of checkpoint-preemption — the trial
        # leaves the device pool terminal-looking (EarlyStopped) but keeps
        # its observation log and checkpoint; a later promotion resubmits it.
        trial.labels[PAUSED_LABEL] = str(k)
        trial.labels[RUNG_LABEL] = str(k)
        trial.set_condition(
            TrialCondition.EARLY_STOPPED,
            "RungPaused",
            f"paused at rung {k} ({ladder.resource_name}="
            f"{ladder.format(ladder.rungs[k])}) awaiting promotion decision"
            + self._bracket_tag(st, b),
        )
        scheduler._record_terminal(exp, trial)
        self._maybe_promote(exp, scheduler)
        return True

    @staticmethod
    def _bracket_tag(st: _ExperimentRungs, b: int) -> str:
        """Bracket suffix for rung events — empty for single-bracket sweeps
        so PR 11 message text stays byte-identical."""
        return f" [bracket {b}]" if len(st.brackets) > 1 else ""

    def _note_bracket_gauge(self, exp_name: str, st: _ExperimentRungs) -> None:
        """katib_bracket_active: brackets that still hold paused or
        dwell-pending members (0 once the ladder drains)."""
        if self.metrics is None:
            return
        with self._lock:
            if st.done:
                live = 0
            else:
                active = {b for b, _ in st.paused.values()}
                active.update(
                    b for _, _, b, _ in self._pending.get(exp_name, ())
                )
                live = len(active)
        self.metrics.set_gauge(
            "katib_bracket_active", float(live), experiment=exp_name
        )

    # -- promotion -----------------------------------------------------------

    def _eligible_locked(self, st: _ExperimentRungs) -> List[Tuple[str, int, int]]:
        """ASHA candidates as (name, bracket, rung), highest rung first
        within each bracket: a paused trial at rung k is promotable while
        it ranks in the top floor(|rung_k| / eta) of every score recorded
        at rung k of its bracket. Caller holds the engine lock."""
        out: List[Tuple[str, int, int]] = []
        for b, br in enumerate(st.brackets):
            for k in range(br.ladder.top - 1, -1, -1):
                records = br.scores[k]
                if not records:
                    continue
                # total promotions out of rung k are capped at the quota:
                # async decisions on a growing rung would otherwise promote
                # every config that was EVER inside the top fraction
                n_promotable = len(records) // br.ladder.eta
                quota_left = n_promotable - len(br.promoted[k])
                if quota_left <= 0:
                    continue
                ranked = sorted(
                    records.items(),
                    key=(
                        (lambda kv: (-kv[1], kv[0]))
                        if st.maximize
                        else (lambda kv: (kv[1], kv[0]))
                    ),
                )
                for name, _ in ranked[:n_promotable]:
                    if quota_left <= 0:
                        break
                    if name in br.promoted[k]:
                        continue
                    if st.paused.get(name) != (b, k):
                        continue  # killed during pause, or still running
                    out.append((name, b, k))
                    quota_left -= 1
        return out

    def _maybe_promote(self, exp: Experiment, scheduler) -> bool:
        """Promote every currently-eligible paused trial. Candidates are
        claimed under the lock (concurrent boundary threads cannot
        double-promote). With no dwell window they submit immediately,
        batched under the scheduler's dispatch barrier; with one, they
        accumulate in the pending buffer until the window expires, the
        sweep goes quiet (drain rule), or the wake timer fires."""
        with self._lock:
            st = self._exps.get(exp.name)
            if st is None or st.done:
                return False
            candidates = self._eligible_locked(st)
            for name, b, k in candidates:
                st.brackets[b].promoted[k].add(name)
                st.paused.pop(name, None)
        if not candidates:
            if self.dwell_seconds > 0:
                return self._flush_if_due(exp, scheduler)
            return False
        if self.dwell_seconds <= 0:
            return self._submit_batch(exp, st, candidates, scheduler, dwelled=False)
        now = time.time()
        with self._lock:
            self._pending.setdefault(exp.name, []).extend(
                (now, name, b, k) for name, b, k in candidates
            )
        self._note_bracket_gauge(exp.name, st)
        if self._sweep_drained(exp):
            # drain rule: nothing is running AND the admission budget is
            # exhausted, so no same-rung peer can ever join the batch —
            # flushing now beats making the last stragglers wait out the
            # window. A merely-momentary quiet gap (more admissions coming)
            # does NOT flush: the wake timer bounds that wait instead, so a
            # mid-sweep lull cannot split a formable pack.
            self._flush_pending(exp, scheduler)
        else:
            self._arm_timer(exp, scheduler)
        return True

    def _sweep_drained(self, exp: Experiment) -> bool:
        trials = self.state.list_trials(exp.name)
        if any(not t.is_terminal for t in trials):
            return False
        maxt = exp.spec.max_trial_count
        return maxt is None or len(trials) >= maxt

    def _arm_timer(self, exp: Experiment, scheduler) -> None:
        """One wake timer per experiment batch so an expired dwell window
        flushes even if no reconcile or boundary fires meanwhile."""
        with self._lock:
            if exp.name in self._timers:
                return
            batch = self._pending.get(exp.name)
            if not batch:
                return
            delay = max(self.dwell_seconds - (time.time() - batch[0][0]), 0.01)
            timer = threading.Timer(
                delay, self._timer_flush, args=(exp.name, scheduler)
            )
            timer.daemon = True
            self._timers[exp.name] = timer
        timer.start()

    def _timer_flush(self, exp_name: str, scheduler) -> None:
        with self._lock:
            self._timers.pop(exp_name, None)
        if getattr(scheduler, "_shutdown", None) is not None and scheduler._shutdown.is_set():
            return
        exp = self.state.get_experiment(exp_name)
        if exp is not None:
            self._flush_pending(exp, scheduler)

    def _flush_if_due(self, exp: Experiment, scheduler) -> bool:
        """Reconcile-side dwell check: flush when the oldest pending
        promotion has waited out the window or the sweep has drained."""
        with self._lock:
            batch = list(self._pending.get(exp.name, ()))
        if not batch:
            return False
        due = time.time() - batch[0][0] >= self.dwell_seconds
        if due or self._sweep_drained(exp):
            return self._flush_pending(exp, scheduler)
        self._arm_timer(exp, scheduler)
        return False

    def _flush_pending(self, exp: Experiment, scheduler) -> bool:
        """Resubmit the whole pending buffer as ONE batch under the
        dispatch barrier, so pack formation sees every same-rung promotion
        together and rung 1+ dispatches as vmapped packs."""
        with self._lock:
            batch = self._pending.pop(exp.name, [])
            timer = self._timers.pop(exp.name, None)
            st = self._exps.get(exp.name)
        if timer is not None:
            timer.cancel()
        if not batch or st is None:
            return False
        candidates = [(name, b, k) for _, name, b, k in batch]
        if self.metrics is not None:
            self.metrics.set_gauge(
                "katib_promotion_pack_size", float(len(candidates)),
                experiment=exp.name,
            )
        if self.events is not None:
            self.events.event(
                exp.name, "Experiment", exp.name, "PromotionBatched",
                f"resubmitting {len(candidates)} dwell-batched promotion(s) "
                f"under one dispatch barrier "
                f"({', '.join(name for name, _, _ in candidates)})",
            )
        return self._submit_batch(exp, st, candidates, scheduler, dwelled=True)

    def _submit_batch(
        self,
        exp: Experiment,
        st: _ExperimentRungs,
        candidates: Sequence[Tuple[str, int, int]],
        scheduler,
        dwelled: bool,
    ) -> bool:
        promoted_any = False
        if self.journal is not None and candidates:
            # intent before action: a crash inside the barrier below leaves
            # the claimed candidates visible to `katib-tpu recover`, and the
            # label rebuild re-derives their paused state on restart
            self.journal.append(
                "promote", exp.name,
                trials=[name for name, _, _ in candidates],
            )
        with scheduler.dispatch_barrier():
            for name, b, k in candidates:
                try:
                    if self._promote_one(
                        exp, name, b, k, st.brackets[b].ladder, scheduler, st
                    ):
                        promoted_any = True
                except Exception:
                    log.warning(
                        "promotion of trial %s failed", name, exc_info=True
                    )
        return promoted_any or dwelled

    def _trial_checkpoint_dir(self, exp: Experiment, trial: Trial, scheduler) -> Optional[str]:
        """Where the trial's previous stint checkpointed: engine trials
        carry no suggester-provided lineage dir, so ctx.checkpoint_store()
        rooted at the per-trial workdir — stable across stints of the same
        trial name, which is exactly what makes the promotion resume work."""
        root = getattr(scheduler, "workdir_root", None)
        if not root:
            return None
        return os.path.join(root, exp.name, trial.name)

    def _checkpoint_restorable(self, ck_dir: Optional[str]) -> bool:
        """True when the paused stint left a loadable checkpoint at the
        store root. A missing or corrupt checkpoint demotes the promotion
        to a re-run-from-scratch (observation log dropped so the fold never
        mixes two executions)."""
        if not ck_dir or not os.path.isdir(ck_dir):
            return False
        from ..runtime.checkpoints import CheckpointStore

        # two attempts: orbax manager construction can transiently fail when
        # probes interleave with other trials' checkpoint traffic in the same
        # process; genuine corruption fails deterministically on both
        for attempt in (0, 1):
            try:
                store = CheckpointStore(ck_dir)
                step = store.latest_step()
                if step is None:
                    return False
                return store.restore(step=step) is not None
            except Exception:
                if attempt == 0:
                    time.sleep(0.05)
                    continue
                log.warning(
                    "checkpoint under %s is unreadable; promoting from scratch",
                    ck_dir, exc_info=True,
                )
        return False

    def _promote_one(
        self,
        exp: Experiment,
        name: str,
        bracket: int,
        k: int,
        ladder: FidelityLadder,
        scheduler,
        st: Optional[_ExperimentRungs] = None,
    ) -> bool:
        trial = self.state.get_trial(exp.name, name)
        if trial is None:
            return False
        if trial.condition != TrialCondition.EARLY_STOPPED or PAUSED_LABEL not in trial.labels:
            if st is not None and not trial.is_terminal:
                # Mid-transition race: on_rung_boundary registers the pause
                # (under the engine lock) BEFORE it persists the
                # EarlyStopped/RungPaused condition, so a concurrent claimer
                # can reach here while the trial still reads Running.
                # Consuming the claim would lose the promotion forever (the
                # trial ends the sweep stuck RungPaused, outside both the
                # paused map and the prune walk) — un-claim instead so the
                # next boundary/pump retries once the transition lands.
                with self._lock:
                    st.brackets[bracket].promoted[k].discard(name)
                    st.paused[name] = (bracket, k)
                return False
            return False  # killed during pause, or already resumed elsewhere
        next_budget = ladder.format(ladder.rungs[k + 1])
        for a in trial.parameter_assignments:
            if a.name == ladder.resource_name:
                a.value = next_budget
        trial.labels.pop(PAUSED_LABEL, None)
        trial.labels[RUNG_LABEL] = str(k + 1)
        ck_dir = self._trial_checkpoint_dir(exp, trial, scheduler)
        fresh = not self._checkpoint_restorable(ck_dir)
        if fresh:
            # re-run-from-scratch fallback: clear the unusable checkpoint so
            # the trial's restore() finds nothing instead of crashing, and
            # drop the prior stint's rows — the same log-can't-mix-two-
            # executions invariant restart requeues enforce
            if ck_dir:
                shutil.rmtree(ck_dir, ignore_errors=True)
            self.obs_store.delete_observation_log(name)
            ck_dir = None
            # promoted trials never serve as duplicate-reuse sources even
            # without a checkpoint_dir marker (their metrics span rungs)
            trial.labels[scheduler.LINEAGE_LABEL] = "1"
        if self.metrics is not None:
            self.metrics.inc("katib_rung_promotions_total", experiment=exp.name)
        if self.events is not None:
            tag = "" if st is None else self._bracket_tag(st, bracket)
            self.events.event(
                exp.name, "Trial", name, "RungPromoted",
                f"promoted from rung {k} to rung {k + 1} "
                f"({ladder.resource_name}={next_budget})"
                + (
                    "; checkpoint missing or unusable, re-running from scratch"
                    if fresh
                    else ", resuming from checkpoint"
                )
                + tag,
            )
        scheduler.submit(exp, trial, checkpoint_dir=ck_dir)
        return True

    # -- reconcile pump / drain ----------------------------------------------

    def pump(self, exp: Experiment, trials: Sequence[Trial], scheduler) -> bool:
        """One reconcile-side pass: promote newly-eligible paused trials
        (they become active again BEFORE status aggregation can declare the
        experiment complete); once the sweep has drained — every trial
        terminal, the admission budget exhausted, nothing left to promote
        or flush — prune the leftover paused trials and close the ladder.
        Returns True when any trial changed state."""
        if not self.applies(exp.spec):
            return False
        try:
            st = self._entry(exp)
        except Exception:
            return False
        with self._lock:
            if st.done:
                return False
        if self._maybe_promote(exp, scheduler):
            return True
        if any(not t.is_terminal for t in trials):
            return False
        with self._lock:
            pending = bool(self._pending.get(exp.name))
        if pending:
            if self._sweep_drained(exp):
                # drain rule: nothing is running and nothing more will be
                # admitted — flush immediately instead of waiting the window
                return self._flush_pending(exp, scheduler)
            return False  # more admissions coming; the wake timer bounds it
        maxt = exp.spec.max_trial_count
        if maxt is not None and len(trials) < maxt:
            return False  # the suggester still has configurations to admit
        return self._prune_leftovers(exp, st)

    def finalize(self, exp: Experiment) -> None:
        """Completion hook (goal reached / budget exhausted): cancel any
        dwell batch — its trials return to the paused set — then prune
        everything still rung-paused so nothing lingers awaiting a
        promotion that will never come."""
        if not self.applies(exp.spec):
            return
        with self._lock:
            st = self._exps.get(exp.name)
            batch = self._pending.pop(exp.name, [])
            timer = self._timers.pop(exp.name, None)
            if st is not None:
                for _, name, b, k in batch:
                    # un-claim: the promotion never happened, so the trial
                    # prunes like any other leftover and the promoted
                    # counts stay truthful
                    st.brackets[b].promoted[k].discard(name)
                    st.paused[name] = (b, k)
        if timer is not None:
            timer.cancel()
        if st is not None:
            self._prune_leftovers(exp, st)

    def _prune_leftovers(self, exp: Experiment, st: _ExperimentRungs) -> bool:
        with self._lock:
            leftovers = sorted(st.paused.items())
            st.paused.clear()
            st.done = True
        pruned = False
        for name, (b, k) in leftovers:
            trial = self.state.get_trial(exp.name, name)
            if trial is None or trial.condition != TrialCondition.EARLY_STOPPED:
                continue
            eta = st.brackets[b].ladder.eta
            tag = self._bracket_tag(st, b)
            trial.labels.pop(PAUSED_LABEL, None)
            trial.set_condition(
                TrialCondition.EARLY_STOPPED,
                "RungPruned",
                f"pruned at rung {k}: outside the top 1/{eta} "
                f"of its rung (observations retained){tag}",
            )
            self.state.update_trial(trial)
            pruned = True
            if self.metrics is not None:
                self.metrics.inc("katib_rung_pruned_total", experiment=exp.name)
            if self.events is not None:
                self.events.event(
                    exp.name, "Trial", name, "RungPruned",
                    f"pruned at rung {k}: outside the top 1/{eta} "
                    f"of its rung{tag}",
                )
        self._note_bracket_gauge(exp.name, st)
        return pruned

    # -- kill-during-pause ---------------------------------------------------

    def kill_paused(self, trial_name: str, scheduler) -> bool:
        """scheduler.kill() hook for trials that are neither queued nor
        running: a rung-paused (or dwell-pending) trial is killed in place
        and permanently removed from its rung's promotion candidates (its
        recorded score still informs the cut for its peers)."""
        exp_name = None
        with self._lock:
            for name, st in self._exps.items():
                if trial_name in st.paused:
                    st.paused.pop(trial_name, None)
                    exp_name = name
                    break
                batch = self._pending.get(name, [])
                kept = [e for e in batch if e[1] != trial_name]
                if len(kept) != len(batch):
                    self._pending[name] = kept
                    exp_name = name
                    break
        if exp_name is None:
            return False
        exp = self.state.get_experiment(exp_name)
        trial = self.state.get_trial(exp_name, trial_name)
        if exp is None or trial is None:
            return False
        trial.labels.pop(PAUSED_LABEL, None)
        trial.set_condition(
            TrialCondition.KILLED, "TrialKilled", "killed while rung-paused"
        )
        self.state.update_trial(trial)
        if self.events is not None:
            self.events.event(
                exp_name, "Trial", trial_name, "TrialKilled",
                "killed while rung-paused",
            )
        from .scheduler import TrialEvent

        scheduler.events.put(TrialEvent(exp_name, trial_name, trial.condition))
        return True

    def forget(self, experiment_name: str) -> None:
        with self._lock:
            self._exps.pop(experiment_name, None)
            self._pending.pop(experiment_name, None)
            timer = self._timers.pop(experiment_name, None)
        if timer is not None:
            timer.cancel()


def pack_rung_key(spec: ExperimentSpec, trial: Trial) -> Optional[str]:
    """Budget value of a multi-fidelity trial, or None for every other
    experiment. Pack formation (controller/packing.py) adds this to the
    open-pack key so members of different rungs never share a vmapped
    program even when semantic analysis has no opinion (no probe): the
    fidelity knob is a host loop count and must be uniform across a pack.
    Brackets share budgets (staggered ladders over the same geometric
    points), so same-budget trials of different brackets still pack."""
    if spec.algorithm.algorithm_name not in ENGINE_ALGORITHMS:
        return None
    resource = spec.algorithm.settings_dict().get("resource_name")
    if not resource:
        return None
    return trial.assignments_dict().get(resource)


def ladder_report(
    spec: ExperimentSpec, trials: Sequence[Trial], store: ObservationStore
) -> Dict[str, Any]:
    """Offline ladder snapshot for `katib-tpu rungs` (and tests): per-
    bracket rung populations, promotions, prunes and per-rung best
    objective, rebuilt purely from persisted trial records + the
    observation store. The legacy top-level ``rungs`` list is bracket 0's
    view (identical to the whole report for single-bracket sweeps);
    ``brackets`` carries every bracket's section."""
    ladders = bracket_ladders(spec)
    maximize = spec.objective.type == ObjectiveType.MAXIMIZE
    reader = ObjectiveCurveReader(store, spec.objective)
    brackets_out: List[Dict[str, Any]] = []
    for b, ladder in enumerate(ladders):
        brackets_out.append(
            {
                "bracket": b,
                "min_resource": ladder.format(ladder.rungs[0]),
                "max_resource": ladder.format(ladder.rungs[-1]),
                "n_rungs": len(ladder.rungs),
                "rungs": [
                    {
                        "rung": k,
                        "budget": ladder.format(r),
                        "population": 0,
                        "running": 0,
                        "paused": 0,
                        "promoted": 0,
                        "pruned": 0,
                        "succeeded": 0,
                        "best": None,
                    }
                    for k, r in enumerate(ladder.rungs)
                ],
            }
        )

    def _rung_index(t: Trial, ladder: FidelityLadder) -> Optional[int]:
        lbl = t.labels.get(RUNG_LABEL)
        if lbl is not None:
            try:
                return min(max(int(lbl), 0), ladder.top)
            except ValueError:
                pass
        value = t.assignments_dict().get(ladder.resource_name)
        if value is None:
            return None
        try:
            return ladder.rung_of(value)
        except ValueError:
            return None

    for t in trials:
        b = _bracket_of(t, len(ladders))
        ladder = ladders[b]
        k = _rung_index(t, ladder)
        if k is None:
            continue
        rungs = brackets_out[b]["rungs"]
        # a trial at rung k passed through (and was promoted out of) every
        # lower rung of its bracket, so it counts toward each rung it
        # trained at
        for j in range(k):
            rungs[j]["population"] += 1
            rungs[j]["promoted"] += 1
        row = rungs[k]
        row["population"] += 1
        if not t.is_terminal:
            row["running"] += 1
        elif t.condition == TrialCondition.SUCCEEDED:
            row["succeeded"] += 1
        elif t.condition == TrialCondition.EARLY_STOPPED:
            if PAUSED_LABEL in t.labels:
                row["paused"] += 1
            else:
                row["pruned"] += 1
        score = reader.boundary_value(t.name)
        if score is not None:
            best = row["best"]
            if best is None or (score > best if maximize else score < best):
                row["best"] = score
    return {
        "experiment": spec.name,
        "resource": ladders[0].resource_name,
        "eta": ladders[0].eta,
        "n_brackets": len(ladders),
        "brackets": brackets_out,
        "rungs": brackets_out[0]["rungs"],
    }
