"""Experiment placement — sharding the control plane across replicas.

PR 14's :class:`~.recovery.ControllerLease` made the whole state root a
single-writer domain; this module generalizes that one lock into
per-experiment *placement leases* under ``<root>/placement/`` so N
controller replicas share one root, each owning a disjoint set of
experiments (upstream Katib gets the same property from the API server's
optimistic concurrency — one controller reconciles an object at a time;
here the lease file IS the placement record):

- ``<root>/placement/<experiment>.lease`` — who runs the experiment: the
  same heartbeated acquire/expire/fence lifecycle as the controller lease
  (dead-pid fast path included, so a SIGKILLed replica's experiments are
  takeable immediately), plus ``replica``/``url`` payload fields so clients
  can route to the owner.
- ``<root>/placement/replicas/<replica>.json`` — the replica registry: one
  heartbeated registration per live replica (rpc url, capacity, claimed
  count). The client router picks the least-loaded live replica for new
  experiments from this table; ``katib-tpu replicas`` renders it offline.

:class:`ReplicaManager` runs inside each replica process: it claims new
experiments up to ``replica_capacity`` (the HTTP create endpoint calls
``claim_new``), heartbeats its claims, and on every supervisor tick scans
for *orphaned* experiments — incomplete, with a takeable lease (expired,
released, or dead holder) — and fails them over: takeover bumps the fence
token, ``load_experiment`` replays the dead replica's journal and truncates
to checkpoints (controller/recovery.py — the machinery is per-experiment
already), and the experiment resumes on this replica
(``ReplicaFailedOver``).

Two survivors can race a takeover scan; the lease write is last-writer-wins
and each claimant re-reads the file after writing, so exactly one keeps the
claim (the loser backs off before loading any state). Scan phases are
additionally staggered per replica id to keep the window rare.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .recovery import ControllerLease, LeaseHeldError, read_lease_path

log = logging.getLogger("katib_tpu.placement")

PLACEMENT_DIRNAME = "placement"
REPLICA_REGISTRY_DIRNAME = "replicas"
LEASE_SUFFIX = ".lease"

ENV_REPLICA_ID = "KATIB_TPU_REPLICA_ID"


def replica_id() -> str:
    """This process's replica identity: ``KATIB_TPU_REPLICA_ID`` when the
    launcher pinned one (the bench names its children), else pid-derived —
    unique per process on one host, which is all the journal subdir and the
    lease owner field need."""
    return os.environ.get(ENV_REPLICA_ID) or f"replica-{os.getpid()}"


def placement_dir(root_dir: str) -> str:
    return os.path.join(root_dir, PLACEMENT_DIRNAME)


def registry_dir(root_dir: str) -> str:
    return os.path.join(placement_dir(root_dir), REPLICA_REGISTRY_DIRNAME)


def lease_file_for(experiment: str) -> str:
    return experiment + LEASE_SUFFIX


def _experiment_completed(root_dir: str, name: str) -> Optional[bool]:
    """Read the persisted experiment record's completion verdict without
    constructing a state store (the failover scan runs every tick). None =
    no readable record (a torn create — not claimable yet)."""
    path = os.path.join(root_dir, "state", name, "state", "experiment.json")
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    for cond in rec.get("status", {}).get("conditions", []):
        if cond.get("type") in ("Succeeded", "Failed") and cond.get("status"):
            return True
    return False


def placement_table(root_dir: str) -> Dict[str, Any]:
    """Offline placement snapshot — replicas + per-experiment leases, read
    straight from ``<root>/placement/`` (the `katib-tpu replicas` CLI and
    the client router both consume this; no controller is constructed, so
    it never contends a live lease)."""
    pdir = placement_dir(root_dir)
    now = time.time()
    replicas: List[Dict[str, Any]] = []
    rdir = registry_dir(root_dir)
    try:
        names = sorted(os.listdir(rdir))
    except OSError:
        names = []
    for fn in names:
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(rdir, fn)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        renewed = float(rec.get("renewed", 0.0) or 0.0)
        ttl = float(rec.get("ttl", 0.0) or 0.0)
        age = now - renewed if renewed else None
        rec["ageSeconds"] = age
        rec["alive"] = bool(
            age is not None and (ttl <= 0 or age <= ttl) and _pid_alive(rec.get("pid"))
        )
        replicas.append(rec)
    leases: List[Dict[str, Any]] = []
    try:
        lease_names = sorted(os.listdir(pdir))
    except OSError:
        lease_names = []
    for fn in lease_names:
        if not fn.endswith(LEASE_SUFFIX):
            continue
        view = read_lease_path(os.path.join(pdir, fn))
        row = view.to_dict()
        row["experiment"] = fn[: -len(LEASE_SUFFIX)]
        row["replica"] = view.payload.get("replica")
        row["url"] = view.payload.get("url")
        leases.append(row)
    return {"root": root_dir, "replicas": replicas, "leases": leases}


def _pid_alive(pid) -> bool:
    try:
        pid = int(pid)
    except (TypeError, ValueError):
        return False
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class ReplicaManager:
    """Claims, heartbeats and fails over experiment placements for ONE
    replica process. Owns no scheduler state — it drives the replica's
    :class:`~.experiment.ExperimentController` through the public
    create/load/run surface, exactly like the UI's run threads."""

    def __init__(
        self,
        controller,
        replica_id: str,
        rpc_url: str = "",
        capacity: int = 8,
        lease_seconds: float = 10.0,
        scan_interval: float = 1.0,
        ingest_addr: str = "",
        wire_tracing: bool = False,
    ):
        self.controller = controller
        self.replica_id = replica_id
        self.rpc_url = rpc_url
        # distributed tracing plane (ISSUE 19): when on, claims and
        # failovers land placement spans in the controller tracer, and a
        # taken-over experiment's later spans are annotated with the bumped
        # fence token — off (default) keeps the span set knob-off identical
        self.wire_tracing = bool(wire_tracing)
        # framed ingest address ("host:port", service/ingest.py) when this
        # replica streams observations on a sibling binary port; "" on the
        # JSON-only wire — surfaced through the registry and status so
        # launchers and the placement table can route streams
        self.ingest_addr = ingest_addr
        self.capacity = max(1, int(capacity))
        self.lease_seconds = max(float(lease_seconds), 1.0)
        self.scan_interval = max(float(scan_interval), 0.1)
        assert controller.root_dir, "sharded placement requires a persisted root"
        self.root_dir = controller.root_dir
        self._pdir = placement_dir(self.root_dir)
        os.makedirs(registry_dir(self.root_dir), exist_ok=True)
        self._lock = threading.Lock()
        self._leases: Dict[str, ControllerLease] = {}
        self._runners: Dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.failovers = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaManager":
        self._register()
        self.controller.events.event(
            "", "Replica", self.replica_id, "ReplicaJoined",
            f"replica {self.replica_id} joined the control plane "
            f"(capacity {self.capacity}, url {self.rpc_url or 'n/a'})",
        )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"placement-{self.replica_id}"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            leases = list(self._leases.values())
            self._leases.clear()
        for lease in leases:
            lease.release()
        try:
            os.remove(self._registration_path())
        except OSError:
            pass

    # -- registry ------------------------------------------------------------

    def _registration_path(self) -> str:
        return os.path.join(registry_dir(self.root_dir), self.replica_id + ".json")

    def _register(self) -> None:
        with self._lock:
            claimed = sorted(self._leases)
        payload = {
            "replica": self.replica_id,
            "pid": os.getpid(),
            "url": self.rpc_url,
            "capacity": self.capacity,
            "claimed": claimed,
            "renewed": time.time(),
            "ttl": self.lease_seconds,
        }
        if self.ingest_addr:
            payload["ingest"] = self.ingest_addr
        path = self._registration_path()
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(payload))
            os.replace(tmp, path)
        except OSError:
            log.debug("replica registration write failed", exc_info=True)
        if self.controller.metrics is not None:
            self.controller.metrics.set_gauge(
                "katib_replica_experiments", float(len(claimed)),
                replica=self.replica_id,
            )

    # -- claims --------------------------------------------------------------

    def claimed(self) -> List[str]:
        with self._lock:
            return sorted(self._leases)

    def status(self) -> Dict[str, Any]:
        out = {
            "replica": self.replica_id,
            "pid": os.getpid(),
            "url": self.rpc_url,
            "capacity": self.capacity,
            "claimed": self.claimed(),
            "failovers": self.failovers,
        }
        if self.ingest_addr:
            out["ingest"] = self.ingest_addr
        return out

    def claim_new(self, experiment: str) -> bool:
        """Claim a freshly-submitted experiment (the HTTP create endpoint).
        False when at capacity or another live replica holds the lease."""
        with self._lock:
            if experiment in self._leases:
                return True  # idempotent re-claim of our own placement
            if len(self._leases) >= self.capacity:
                return False
        return self._claim(experiment) is not None

    def release(self, experiment: str) -> None:
        with self._lock:
            lease = self._leases.pop(experiment, None)
            self._runners.pop(experiment, None)
        if lease is not None:
            lease.release()
        self._register()

    def _tracer(self):
        """The controller tracer, only when the wire-tracing knob is on
        (placement spans are part of the gated distributed span set)."""
        if not self.wire_tracing:
            return None
        tracer = getattr(self.controller, "tracer", None)
        if tracer is None or not tracer.enabled:
            return None
        return tracer

    def _claim(self, experiment: str) -> Optional[ControllerLease]:
        t0 = time.time()
        lease = ControllerLease(
            self._pdir,
            ttl_seconds=self.lease_seconds,
            events=self.controller.events,
            metrics=self.controller.metrics,
            lease_file=lease_file_for(experiment),
            owner=self.replica_id,
            extra={"replica": self.replica_id, "url": self.rpc_url},
            pid_reacquire=False,
        )
        try:
            lease.acquire()
        except LeaseHeldError:
            return None
        # last-writer-wins double-check: a concurrent claimant may have
        # overwritten our record between _write and now — re-read and keep
        # the claim only if the file still names us
        view = read_lease_path(lease.path)
        if view.payload.get("owner") != self.replica_id:
            lease.lost.set()
            lease.release()
            return None
        with self._lock:
            self._leases[experiment] = lease
        self._register()
        tracer = self._tracer()
        if tracer is not None:
            tracer.record_span(
                "placement.claim", experiment, tracer.new_trace_id(), None,
                start=t0, end=time.time(),
                replica=self.replica_id, fence=lease.fence,
            )
        return lease

    # -- run threads ---------------------------------------------------------

    def run_experiment(self, experiment: str) -> None:
        """Drive a claimed experiment to completion on a daemon thread (the
        ui/server.py run-thread shape); the placement lease is released when
        the run ends so the table shows completed experiments unowned."""

        def _run():
            try:
                self.controller.run(experiment)
            except KeyError:
                pass  # deleted while running
            except Exception:
                log.exception("replica run thread failed for %s", experiment)
            finally:
                self.release(experiment)

        t = threading.Thread(
            target=_run, daemon=True, name=f"replica-run-{experiment}"
        )
        with self._lock:
            self._runners[experiment] = t
        t.start()

    # -- supervisor ----------------------------------------------------------

    def _loop(self) -> None:
        # deterministic stagger so same-tick takeover races between
        # survivors stay rare (the double-check in _claim resolves the rest)
        offset = (hash(self.replica_id) % 7) * self.scan_interval / 8.0
        self._stop.wait(offset)
        while not self._stop.wait(self.scan_interval):
            try:
                self._register()
                self._tick()
            except Exception:
                log.exception("placement tick failed")

    def _tick(self) -> None:
        with self._lock:
            free = self.capacity - len(self._leases)
        if free <= 0:
            return
        state_root = os.path.join(self.root_dir, "state")
        try:
            names = sorted(os.listdir(state_root))
        except OSError:
            return
        for name in names:
            if free <= 0:
                return
            with self._lock:
                if name in self._leases:
                    continue
            if not os.path.isdir(os.path.join(state_root, name)):
                continue
            completed = _experiment_completed(self.root_dir, name)
            if completed is None or completed:
                continue
            view = read_lease_path(os.path.join(self._pdir, lease_file_for(name)))
            if not view.exists:
                # never placed (a crash between create and claim): claimable
                pass
            elif view.state == "active" and not view.expired and view.holder_alive:
                continue  # live owner
            t0 = time.time()
            lease = self._claim(name)
            if lease is None:
                continue
            free -= 1
            self.failovers += 1
            tracer = self._tracer()
            if tracer is not None:
                # every span the resumed experiment records from here on
                # carries the bumped fence token — the takeover is visible
                # in the merged cross-replica tree, not just the event log
                tracer.annotate(name, fence=lease.fence, failedOverTo=self.replica_id)
                tracer.record_span(
                    "placement.failover", name, tracer.new_trace_id(), None,
                    start=t0, end=time.time(),
                    replica=self.replica_id, fence=lease.fence,
                    takenFrom=view.payload.get("replica") or "",
                )
            self.controller.events.event(
                name, "Replica", self.replica_id, "ReplicaFailedOver",
                f"replica {self.replica_id} took over experiment {name} "
                f"from {view.payload.get('replica') or 'nobody'} "
                f"(fence {lease.fence}); recovering from the shared root",
                warning=True,
            )
            try:
                self.controller.load_experiment(name)
            except Exception:
                log.exception("failover load of %s failed", name)
                self.release(name)
                continue
            self.run_experiment(name)
