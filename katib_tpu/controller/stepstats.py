"""Controller half of the step-statistics plane (ISSUE 20).

The runtime half (katib_tpu/runtime/stepstats.py) measures each stint: per
step wall durations, throughput volume, recompiles. This plane owns what
happens when a stint ENDS — stint rows written through the observation
pipeline, per-experiment rollups exported on /metrics, and the three
detectors:

- ``RetraceStorm``: one stint recompiled more than
  ``runtime.retrace_storm_threshold`` times past the first compile — the
  classic symptom of a shape-unstable train loop burning its step budget on
  XLA retraces.
- ``GangStraggler``: a packed/fused member's p95 step time exceeds the gang
  median by ``runtime.straggler_ratio`` — the packing plane's first
  slowest-member visibility (Podracer-style schedulers tune off exactly
  this, arXiv:2104.06272).
- ``StepTimeRegression``: a resumed/promoted stint is measurably slower
  than the same trial's prior-stint baseline (read back from the persisted
  perf rows), past ``runtime.step_regression_ratio``.

Constructed only when ``runtime.step_stats`` is on; every consult from the
scheduler is one ``is None`` check when it is off.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..runtime.stepstats import PERF_PREFIX, StepClock, StintSummary, perf_logs

# stint summaries kept per experiment for the /metrics rollups
ROLLUP_STINTS = 512

_P50_ROW = PERF_PREFIX + "stint_step_seconds_p50"


class _ExpRollup:
    __slots__ = (
        "stint_p50s", "stint_p95s", "total_steps", "total_seconds",
        "total_examples", "last_mfu", "device_seconds", "best_objective",
    )

    def __init__(self) -> None:
        self.stint_p50s: deque = deque(maxlen=ROLLUP_STINTS)
        self.stint_p95s: deque = deque(maxlen=ROLLUP_STINTS)
        self.total_steps = 0
        self.total_seconds = 0.0
        self.total_examples = 0.0
        self.last_mfu: Optional[float] = None
        self.device_seconds = 0.0
        self.best_objective: Optional[float] = None


class StepStatsPlane:
    """Per-experiment perf rollups + stint finalization + detectors."""

    def __init__(
        self,
        metrics: Optional[Any] = None,
        events: Optional[Any] = None,
        flush_steps: int = 32,
        retrace_storm_threshold: int = 8,
        straggler_ratio: float = 2.0,
        regression_ratio: float = 1.5,
    ) -> None:
        self.metrics = metrics
        self.events = events
        self.flush_steps = flush_steps
        self.retrace_storm_threshold = retrace_storm_threshold
        self.straggler_ratio = straggler_ratio
        self.regression_ratio = regression_ratio
        self._lock = threading.Lock()
        self._rollups: Dict[str, _ExpRollup] = {}
        self._cost_cache: Dict[str, Any] = {}
        self._device_kind: Optional[str] = None
        self._device_kind_probed = False
        if metrics is not None:
            metrics.add_collector(
                self._collect,
                names=(
                    "katib_step_seconds",
                    "katib_trial_throughput",
                    "katib_trial_mfu_ratio",
                    "katib_objective_per_device_second",
                ),
            )

    # -- clock factory -------------------------------------------------------

    def clock_for(self, member_index: Optional[int] = None) -> StepClock:
        return StepClock(flush_steps=self.flush_steps, member_index=member_index)

    # -- stint finalization --------------------------------------------------

    def finalize_stint(
        self,
        exp: Any,
        trial_name: str,
        clock: StepClock,
        store: Any,
        n_devices: int = 1,
        write_rows: bool = True,
    ) -> Optional[StintSummary]:
        """A stint ended (trial finished, rung-paused, early-stopped, ...).

        Writes the stint-level perf rows, fires RetraceStorm and
        StepTimeRegression, and folds the summary into the experiment
        rollup. ``write_rows=False`` skips persistence for stints whose
        rows are about to be discarded anyway (preempt-requeue truncates to
        the last checkpoint; the resumed stint re-measures)."""
        rows, summary = clock.finalize()
        if summary.steps <= 0:
            return None
        mfu_value = self._mfu_for(exp, summary, n_devices)
        if mfu_value is not None and write_rows:
            rows.append(("stint_mfu", mfu_value))
        exp_name = getattr(exp, "name", str(exp))
        baseline = None
        if write_rows and store is not None:
            # prior stint rows identify a resumed/promoted stint — and are
            # the StepTimeRegression baseline (earliest stint = the
            # cheapest-fidelity reference)
            try:
                prior = store.get_observation_log(trial_name, metric_name=_P50_ROW)
            except Exception:
                prior = []
            for log in prior:
                try:
                    baseline = float(log.value)
                    break
                except (TypeError, ValueError):
                    continue
            try:
                store.report_observation_log(trial_name, perf_logs(rows))
                store.flush()  # later stints read these back as baselines
            except Exception:
                pass
        self._detect_retrace_storm(exp_name, trial_name, summary)
        if baseline is not None and baseline > 0 and summary.p50 > 0:
            self._detect_regression(exp_name, trial_name, summary, baseline)
        self._absorb(exp_name, summary, mfu_value)
        return summary

    def finalize_pack(
        self,
        exp: Any,
        trial_names: Sequence[str],
        clocks: Sequence[StepClock],
        store: Any,
        n_devices: int = 1,
        requeued: Sequence[bool] = (),
    ) -> None:
        """Finalize every member's stint, then run the gang-level straggler
        detector over the members that actually stepped."""
        summaries: List[Tuple[str, StintSummary]] = []
        for i, (name, clock) in enumerate(zip(trial_names, clocks)):
            skip = bool(requeued[i]) if i < len(requeued) else False
            s = self.finalize_stint(
                exp, name, clock, store,
                n_devices=max(1, n_devices // max(1, len(trial_names))),
                write_rows=not skip,
            )
            if s is not None:
                summaries.append((name, s))
        if len(summaries) < 2:
            return
        exp_name = getattr(exp, "name", str(exp))
        p95s = sorted(s.p95 for _, s in summaries)
        median = p95s[len(p95s) // 2]
        if median <= 0:
            return
        for name, s in summaries:
            if s.p95 > self.straggler_ratio * median:
                self._warn(
                    exp_name, name, "GangStraggler",
                    f"pack member p95 step time {s.p95:.4f}s exceeds gang "
                    f"median {median:.4f}s by more than "
                    f"{self.straggler_ratio:g}x",
                )

    # -- detectors -----------------------------------------------------------

    def _detect_retrace_storm(
        self, exp_name: str, trial_name: str, summary: StintSummary
    ) -> None:
        if self.metrics is not None and summary.retraces > 0:
            self.metrics.inc(
                "katib_trial_retraces_total", float(summary.retraces),
                experiment=exp_name,
            )
        if summary.retraces > self.retrace_storm_threshold:
            self._warn(
                exp_name, trial_name, "RetraceStorm",
                f"stint recompiled {summary.retraces} times past the first "
                f"compile (threshold {self.retrace_storm_threshold}); the "
                "train loop is likely shape-unstable",
            )

    def _detect_regression(
        self, exp_name: str, trial_name: str, summary: StintSummary, baseline: float
    ) -> None:
        if summary.p50 > self.regression_ratio * baseline:
            self._warn(
                exp_name, trial_name, "StepTimeRegression",
                f"resumed stint p50 step time {summary.p50:.4f}s is more "
                f"than {self.regression_ratio:g}x the trial's prior-stint "
                f"baseline {baseline:.4f}s",
            )

    def _warn(self, exp_name: str, trial_name: str, reason: str, message: str) -> None:
        if self.events is not None:
            self.events.event(
                exp_name, "Trial", trial_name, reason, message, warning=True
            )

    # -- rollups -------------------------------------------------------------

    def _absorb(
        self, exp_name: str, summary: StintSummary, mfu_value: Optional[float]
    ) -> None:
        with self._lock:
            r = self._rollups.setdefault(exp_name, _ExpRollup())
            if summary.p50 > 0:
                r.stint_p50s.append(summary.p50)
                r.stint_p95s.append(summary.p95)
            r.total_steps += summary.steps
            r.total_seconds += summary.seconds
            r.total_examples += summary.examples
            if mfu_value is not None:
                r.last_mfu = mfu_value

    def charge_device_seconds(self, exp_name: str, seconds: float) -> None:
        """Gang-release hook: accumulate device-seconds so the rollup can
        export objective-per-device-second (ROADMAP item 3c's admission
        signal; read-side only, no scheduling behavior change)."""
        if seconds <= 0:
            return
        with self._lock:
            r = self._rollups.setdefault(exp_name, _ExpRollup())
            r.device_seconds += seconds

    def note_objective(self, exp_name: str, value: float, maximize: bool) -> None:
        """Track the experiment's best objective for the per-device-second
        rollup (direction-aware: max for maximize, min for minimize)."""
        with self._lock:
            r = self._rollups.setdefault(exp_name, _ExpRollup())
            if r.best_objective is None:
                r.best_objective = value
            elif maximize:
                r.best_objective = max(r.best_objective, value)
            else:
                r.best_objective = min(r.best_objective, value)

    def forget_experiment(self, exp_name: str) -> None:
        with self._lock:
            self._rollups.pop(exp_name, None)
            self._cost_cache.pop(exp_name, None)

    def _collect(self) -> Dict:
        """Per-scrape gauge recompute (MetricsRegistry.add_collector)."""
        if self.metrics is None:
            return {}
        key = self.metrics.gauge_key
        out: Dict = {}
        with self._lock:
            items = list(self._rollups.items())
        for exp_name, r in items:
            p50s = sorted(r.stint_p50s)
            if p50s:
                out[key("katib_step_seconds", experiment=exp_name, quantile="p50")] = (
                    p50s[len(p50s) // 2]
                )
                out[key("katib_step_seconds", experiment=exp_name, quantile="p95")] = (
                    max(r.stint_p95s)
                )
            if r.total_seconds > 0:
                out[key("katib_trial_throughput", experiment=exp_name)] = (
                    r.total_steps / r.total_seconds
                )
            if r.last_mfu is not None:
                out[key("katib_trial_mfu_ratio", experiment=exp_name)] = r.last_mfu
            if r.best_objective is not None and r.device_seconds > 0:
                out[key("katib_objective_per_device_second", experiment=exp_name)] = (
                    r.best_objective / r.device_seconds
                )
        return out

    # -- MFU plumbing --------------------------------------------------------

    def _mfu_for(
        self, exp: Any, summary: StintSummary, n_devices: int
    ) -> Optional[float]:
        if summary.p50 <= 0:
            return None
        from ..analysis.costmodel import mfu

        return mfu(
            self._cost_for(exp), summary.p50, max(1, n_devices),
            device_kind=self._probe_device_kind(),
        )

    def _cost_for(self, exp: Any) -> Optional[Any]:
        """CostEstimate of one traced step for this experiment's template —
        the same static analysis the PR 7/8 compile plane runs, cached per
        experiment. None when the template has no probe (no MFU then)."""
        name = getattr(exp, "name", str(exp))
        with self._lock:
            if name in self._cost_cache:
                return self._cost_cache[name]
        cost = None
        try:
            from ..analysis.program import cached_analysis

            analysis = cached_analysis(exp.spec)
            cost = getattr(analysis, "cost", None) if analysis is not None else None
        except Exception:
            cost = None
        with self._lock:
            self._cost_cache[name] = cost
        return cost

    def _probe_device_kind(self) -> Optional[str]:
        if self._device_kind_probed:
            return self._device_kind
        self._device_kind_probed = True
        try:
            from ..utils.backend import bounded_devices

            devs = bounded_devices()
            self._device_kind = devs[0].device_kind if devs else None
        except Exception:
            self._device_kind = None
        return self._device_kind
