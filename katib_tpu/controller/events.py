"""Event recording + Prometheus-style controller metrics.

reference observability surface (SURVEY.md §5):
- K8s Events on every state change (r.recorder.Eventf —
  trial_controller_util.go:66/86/109);
- Prometheus CounterVec/GaugeVec for experiments/trials
  created/succeeded/failed/deleted (experiment/util/prometheus_metrics.go:29-78,
  trial/util/prometheus_metrics.go).

Here: an in-memory (optionally persisted) ring of typed events per
experiment, and a metrics registry rendered in Prometheus text exposition
format (served by katib_tpu.ui.server at /metrics).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple


@dataclass
class Event:
    timestamp: float
    kind: str          # Experiment | Trial
    name: str
    event_type: str    # Normal | Warning
    reason: str
    message: str

    def to_dict(self):
        return {
            "timestamp": self.timestamp,
            "kind": self.kind,
            "name": self.name,
            "type": self.event_type,
            "reason": self.reason,
            "message": self.message,
        }


class EventRecorder:
    def __init__(self, max_events: int = 1000):
        self._lock = threading.Lock()
        self._events: Dict[str, Deque[Event]] = {}
        self.max_events = max_events

    def event(
        self,
        experiment: str,
        kind: str,
        name: str,
        reason: str,
        message: str,
        warning: bool = False,
    ) -> None:
        e = Event(
            timestamp=time.time(),
            kind=kind,
            name=name,
            event_type="Warning" if warning else "Normal",
            reason=reason,
            message=message,
        )
        with self._lock:
            q = self._events.setdefault(experiment, collections.deque(maxlen=self.max_events))
            q.append(e)

    def list(self, experiment: str) -> List[Event]:
        with self._lock:
            return list(self._events.get(experiment, ()))


class MetricsRegistry:
    """Counters/gauges labelled by experiment, Prometheus text format.

    Metric names mirror the reference: katib_experiment_created_total,
    katib_experiment_succeeded_total, katib_experiment_failed_total,
    katib_trial_created_total, katib_trial_succeeded_total,
    katib_trial_failed_total, katib_trial_early_stopped_total, plus running
    gauges (prometheus_metrics.go).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._collector = None  # per-scrape gauge recompute hook
        self._collector_names: Tuple[str, ...] = ()
        self._collector_error_logged = False

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    @staticmethod
    def gauge_key(name: str, **labels: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
        """Key builder for collector result dicts (see set_collector)."""
        return (name, tuple(sorted(labels.items())))

    def set_collector(self, fn, names: Tuple[str, ...] = ()) -> None:
        """Register a hook invoked at the start of every render(): the
        reference's custom-collector pattern (prometheus_metrics.go collect)
        — current-state gauges are recomputed from live state per scrape, so
        they can't go stale through any mutation path. ``fn`` returns a dict
        of ``gauge_key(...) -> value``; ``names`` declares which gauge names
        the collector owns. render() swaps every series of the owned names in
        ONE lock acquisition, so a concurrent scrape never observes a
        cleared-but-not-yet-repopulated registry, and owned series vanish
        when the collector returns none for them (deleted experiments)."""
        self._collector = fn
        self._collector_names = tuple(names)

    def render(self) -> str:
        """Prometheus text exposition format."""
        if self._collector is not None:
            try:
                collected = self._collector()
            except Exception:
                # a scrape must not fail because state was mid-mutation —
                # but a persistent collector bug must not be silent either
                if not self._collector_error_logged:
                    self._collector_error_logged = True
                    logging.getLogger("katib_tpu.metrics").exception(
                        "gauge collector failed; current-state gauges frozen "
                        "(logged once)"
                    )
                collected = None
            if collected is not None:
                names = set(self._collector_names) | {key[0] for key in collected}
                with self._lock:
                    for key in [k for k in self._gauges if k[0] in names]:
                        del self._gauges[key]
                    self._gauges.update(collected)
        lines: List[str] = []
        with self._lock:
            for (name, labels), value in sorted(self._counters.items()):
                lines.append(f"# TYPE {name} counter") if f"# TYPE {name} counter" not in lines else None
                lines.append(f"{_series(name, labels)} {value}")
            for (name, labels), value in sorted(self._gauges.items()):
                lines.append(f"# TYPE {name} gauge") if f"# TYPE {name} gauge" not in lines else None
                lines.append(f"{_series(name, labels)} {value}")
        return "\n".join(lines) + "\n"


def _series(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    """Series head; unlabelled series (the obslog pipeline counters) must
    render bare — `name{}` trips strict exposition parsers."""
    if not labels:
        return name
    return name + "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
