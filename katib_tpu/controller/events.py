"""Event recording + Prometheus-style controller metrics.

reference observability surface (SURVEY.md §5):
- K8s Events on every state change (r.recorder.Eventf —
  trial_controller_util.go:66/86/109);
- Prometheus CounterVec/GaugeVec for experiments/trials
  created/succeeded/failed/deleted (experiment/util/prometheus_metrics.go:29-78,
  trial/util/prometheus_metrics.go).

Here: an in-memory (optionally persisted) ring of typed events per
experiment, and a metrics registry rendered in Prometheus text exposition
format (served by katib_tpu.ui.server at /metrics).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple


@dataclass
class Event:
    timestamp: float
    kind: str          # Experiment | Trial
    name: str
    event_type: str    # Normal | Warning
    reason: str
    message: str
    experiment: str = ""  # owning experiment — the cross-experiment view key

    def to_dict(self):
        return {
            "timestamp": self.timestamp,
            "kind": self.kind,
            "name": self.name,
            "type": self.event_type,
            "reason": self.reason,
            "message": self.message,
            "experiment": self.experiment,
        }


class EventRecorder:
    def __init__(self, max_events: int = 1000):
        self._lock = threading.Lock()
        self._events: Dict[str, Deque[Event]] = {}
        self.max_events = max_events

    def event(
        self,
        experiment: str,
        kind: str,
        name: str,
        reason: str,
        message: str,
        warning: bool = False,
    ) -> None:
        e = Event(
            timestamp=time.time(),
            kind=kind,
            name=name,
            event_type="Warning" if warning else "Normal",
            reason=reason,
            message=message,
            experiment=experiment,
        )
        with self._lock:
            q = self._events.setdefault(experiment, collections.deque(maxlen=self.max_events))
            q.append(e)

    def list(self, experiment: str) -> List[Event]:
        with self._lock:
            return list(self._events.get(experiment, ()))

    def list_all(
        self, limit: Optional[int] = None, warning_only: bool = False
    ) -> List[Event]:
        """Cross-experiment event view, oldest first: queue stalls,
        preemptions and flusher errors are queryable without knowing which
        experiment raised them (GET /api/events?warning=1)."""
        with self._lock:
            merged = [e for q in self._events.values() for e in q]
        merged.sort(key=lambda e: e.timestamp)
        if warning_only:
            merged = [e for e in merged if e.event_type == "Warning"]
        if limit is not None:
            merged = merged[-limit:] if limit > 0 else []
        return merged


class _Histogram:
    """Fixed-bucket histogram state: per-bucket counts (non-cumulative in
    memory, rendered cumulatively), running sum and count."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.counts[i] += 1
                break


class MetricsRegistry:
    """Counters/gauges/histograms labelled by experiment, Prometheus text
    format.

    Metric names mirror the reference: katib_experiment_created_total,
    katib_experiment_succeeded_total, katib_experiment_failed_total,
    katib_trial_created_total, katib_trial_succeeded_total,
    katib_trial_failed_total, katib_trial_early_stopped_total, plus running
    gauges (prometheus_metrics.go). Histograms (no reference counterpart —
    its exporter is counters/gauges only) render the full
    ``_bucket``/``_sum``/``_count`` exposition series; the tracing layer
    feeds katib_span_duration_seconds{stage=...} through observe().
    """

    # latency-shaped default buckets: 1ms .. 10min, roughly log-spaced
    DEFAULT_BUCKETS: Tuple[float, ...] = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], _Histogram] = {}
        self._help: Dict[str, str] = {}
        # per-scrape gauge recompute hooks: [(fn, owned gauge names), ...]
        self._collectors: List[Tuple[object, Tuple[str, ...]]] = []
        self._collector_error_logged = False

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def observe(
        self,
        name: str,
        value: float,
        buckets: Optional[Tuple[float, ...]] = None,
        **labels: str,
    ) -> None:
        """Record one histogram observation. The bucket layout is fixed by
        the first observation of a series; later ``buckets`` arguments are
        ignored (exposition series must keep a stable layout)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = _Histogram(
                    tuple(buckets) if buckets else self.DEFAULT_BUCKETS
                )
            h.observe(value)

    def set_help(self, name: str, text: str) -> None:
        """One-line # HELP text for a metric name (single line; newlines
        would corrupt the exposition)."""
        with self._lock:
            self._help[name] = " ".join(str(text).split())

    @staticmethod
    def gauge_key(name: str, **labels: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
        """Key builder for collector result dicts (see set_collector)."""
        return (name, tuple(sorted(labels.items())))

    def set_collector(self, fn, names: Tuple[str, ...] = ()) -> None:
        """Register a hook invoked at the start of every render(): the
        reference's custom-collector pattern (prometheus_metrics.go collect)
        — current-state gauges are recomputed from live state per scrape, so
        they can't go stale through any mutation path. ``fn`` returns a dict
        of ``gauge_key(...) -> value``; ``names`` declares which gauge names
        the collector owns. render() swaps every series of the owned names in
        ONE lock acquisition, so a concurrent scrape never observes a
        cleared-but-not-yet-repopulated registry, and owned series vanish
        when the collector returns none for them (deleted experiments).

        Legacy single-collector surface: REPLACES every registered hook.
        Subsystems sharing one registry (controller status gauges + the
        telemetry sampler) use :meth:`add_collector` instead."""
        with self._lock:
            self._collectors = [(fn, tuple(names))]

    def add_collector(self, fn, names: Tuple[str, ...] = ()) -> None:
        """Append a collector hook (same contract as set_collector); each
        hook owns a disjoint set of gauge names. Registration happens from
        subsystem constructors on whatever thread builds them — locked, so a
        concurrent scrape's hook iteration never sees a half-appended list."""
        with self._lock:
            self._collectors.append((fn, tuple(names)))

    def render(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            collectors = list(self._collectors)
        if collectors:
            merged: Dict = {}
            names: set = set()
            for fn, owned in collectors:
                try:
                    collected = fn()
                except Exception:
                    # a scrape must not fail because state was mid-mutation —
                    # but a persistent collector bug must not be silent
                    # either; a failing hook's owned gauges stay frozen
                    # while the other hooks keep collecting
                    if not self._collector_error_logged:
                        self._collector_error_logged = True
                        logging.getLogger("katib_tpu.metrics").exception(
                            "gauge collector failed; its current-state gauges "
                            "frozen (logged once)"
                        )
                    continue
                if collected is None:
                    continue
                merged.update(collected)
                names |= set(owned) | {key[0] for key in collected}
            if names or merged:
                with self._lock:
                    for key in [k for k in self._gauges if k[0] in names]:
                        del self._gauges[key]
                    self._gauges.update(merged)
        lines: List[str] = []
        # O(1) dedup of the per-name metadata lines — the old
        # `lines.append(...) if ... not in lines else None` idiom was an
        # O(n²) membership scan wrapped in an expression statement
        seen: set = set()

        def _meta(name: str, kind: str) -> None:
            if name in seen:
                return
            seen.add(name)
            lines.append(f"# HELP {name} {self._help.get(name, _default_help(name, kind))}")
            lines.append(f"# TYPE {name} {kind}")

        with self._lock:
            for (name, labels), value in sorted(self._counters.items()):
                _meta(name, "counter")
                lines.append(f"{_series(name, labels)} {value}")
            for (name, labels), value in sorted(self._gauges.items()):
                _meta(name, "gauge")
                lines.append(f"{_series(name, labels)} {value}")
            for (name, labels), h in sorted(self._histograms.items()):
                _meta(name, "histogram")
                cumulative = 0
                for le, count in zip(h.buckets, h.counts):
                    cumulative += count
                    lines.append(
                        f"{_series(name + '_bucket', labels + (('le', _fmt_le(le)),))} "
                        f"{float(cumulative)}"
                    )
                lines.append(
                    f"{_series(name + '_bucket', labels + (('le', '+Inf'),))} "
                    f"{float(h.count)}"
                )
                lines.append(f"{_series(name + '_sum', labels)} {h.sum}")
                lines.append(f"{_series(name + '_count', labels)} {float(h.count)}")
        return "\n".join(lines) + "\n"


def _series(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    """Series head; unlabelled series (the obslog pipeline counters) must
    render bare — `name{}` trips strict exposition parsers."""
    if not labels:
        return name
    return name + "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"


def _fmt_le(le: float) -> str:
    """Prometheus-conventional bucket bound rendering: 0.005, 1, 30."""
    return f"{le:g}"


# HELP text for the katib_* catalog (docs/observability.md); names outside
# the catalog get a generated one-liner so every family still carries HELP.
_HELP_CATALOG: Dict[str, str] = {
    "katib_experiment_created_total": "Experiments created.",
    "katib_experiment_succeeded_total": "Experiments that completed successfully.",
    "katib_experiment_failed_total": "Experiments that completed failed.",
    "katib_experiment_deleted_total": "Experiments deleted.",
    "katib_experiments_current": "Experiments by current status (recomputed per scrape).",
    "katib_trial_created_total": "Trials created.",
    "katib_trial_succeeded_total": "Trials that succeeded.",
    "katib_trial_failed_total": "Trials that failed.",
    "katib_trial_killed_total": "Trials killed.",
    "katib_trial_early_stopped_total": "Trials early-stopped.",
    "katib_trial_metrics_unavailable_total": "Trials finishing without objective metrics.",
    "katib_trial_completed_total": "Trials completed (other terminal states).",
    "katib_trial_preempted_total": "Trial preemptions by the fair-share policy.",
    "katib_trials_current": "Trials by current condition (recomputed per scrape).",
    "katib_queue_depth": "Pending trials per experiment after the last dispatch pass.",
    "katib_queue_wait_seconds": "Oldest pending trial's wait per experiment.",
    "katib_fairshare_deficit": "Fair-share deficit (normalized device-seconds) per experiment.",
    "katib_pack_formed_total": "Trial packs formed (vmapped multi-trial programs).",
    "katib_trial_packed_total": "Trials dispatched as pack members.",
    "katib_pack_occupancy": "Members / capacity of the most recent pack.",
    "katib_obslog_flush_total": "Group-commit flushes of the buffered observation store.",
    "katib_obslog_flush_batch_rows": "Rows drained by buffered-store flushes.",
    "katib_obslog_flush_latency_seconds": "Latency of the last buffered-store flush.",
    "katib_obslog_buffered_rows": "Rows currently buffered in the write-behind store.",
    "katib_span_duration_seconds": "Trial lifecycle stage durations from tracing spans, by stage.",
    # resource telemetry + health watchdog (katib_tpu/telemetry.py) — the
    # TrialStalled / TrialOOMRisk warning events pair with these counters
    # and show in GET /api/events?warning=1
    "katib_telemetry_samples_total": "Per-trial resource samples recorded by the telemetry sampler.",
    "katib_trial_stalled_total": "Trials flagged by the watchdog: no report heartbeat past runtime.stall_seconds.",
    "katib_trial_oom_risk_total": "Trials whose monotonic RSS growth crossed the OOM-risk fraction of host memory.",
    "katib_trial_host_rss_bytes": "Latest sampled host RSS per running trial (/proc; in-process trials share the controller process).",
    "katib_trial_cpu_percent": "Latest sampled CPU utilization per running trial (percent of one core).",
    "katib_device_hbm_used_bytes": "Accelerator memory in use per local device (jax memory_stats).",
    "katib_xla_cache_entries": "Entries in the persistent XLA compilation cache.",
    "katib_xla_cache_bytes": "Total size of the persistent XLA compilation cache.",
    # AOT compile service (katib_tpu/compilesvc) — the CompileFailed /
    # BackendInitFailed warning events pair with these series
    "katib_compile_queue_depth": "Compile jobs queued in the AOT compile service (cost-ordered).",
    "katib_compile_cache_hit_total": "Trial submissions whose dispatch group was already warm in the executable registry.",
    "katib_compile_cache_miss_total": "Trial submissions whose dispatch group was not yet warm (pending/compiling/new/failed).",
    "katib_compile_failed_total": "AOT compiles that failed or timed out; the fingerprint group is quarantined.",
    "katib_compile_seconds": "Wall-clock of AOT compiles executed by the service, per experiment.",
    # fused population loops (katib_tpu/runtime/population.py, ISSUE 9)
    "katib_population_generations_total": "PBT/ENAS generations executed by the fused population runtime.",
    "katib_population_fused_seconds": "Wall-clock of fused population scan chunks (one compiled program per chunk).",
    # vectorized / async suggestion plane (ISSUE 10, suggest/vectorized.py
    # + controller/suggestion.py) — WarmStartApplied pairs with the
    # warm-start counter
    "katib_suggestion_batch_seconds": "Wall-clock of suggestion batch computes, by algorithm and mode (inline vs prefetch).",
    "katib_suggestion_buffer_ready_total": "Assignments served from the async prefetch buffer.",
    "katib_suggestion_buffer_miss_total": "Buffer consults that fell back to the inline compute (cold or stale buffer).",
    "katib_warm_start_total": "Experiments whose suggester was seeded from matching completed-experiment history.",
    # native multi-fidelity search (katib_tpu/controller/multifidelity.py,
    # ISSUE 11) — the RungPaused / RungPromoted / RungPruned events pair
    # with these series
    "katib_rung_promotions_total": "Rung-paused trials promoted to the next fidelity (checkpoint-resumed or re-run from scratch).",
    "katib_rung_pruned_total": "Rung-paused trials pruned when the ladder drained (outside the top 1/eta of their rung).",
    "katib_multifidelity_device_seconds": "Device-seconds consumed by multi-fidelity (asha/bohb) trial stints, charged at gang release.",
    # model-based multi-fidelity + dwell-window promotion packing (ISSUE 13)
    "katib_bracket_active": "Hyperband brackets that still hold rung-paused or dwell-pending trials, per experiment.",
    "katib_promotion_pack_size": "Size of the most recent dwell-batched promotion resubmission (rung 1+ pack seed).",
    # supervised device plane (ISSUE 12, controller/deviceplane.py) — the
    # DeviceLost / DeviceLeaseRevoked / BackendFailedOver warning events
    # pair with these series
    "katib_device_lease_granted_total": "Device leases granted by the supervised device plane (one per gang allocation).",
    "katib_device_lease_revoked_total": "Leases the plane revoked: expired zombie holds reclaimed or heartbeat-missed holders voided.",
    "katib_device_lease_active": "Leases currently in ACTIVE state (holders running on their devices).",
    "katib_device_lease_zombie": "Leases in ZOMBIE state (abandoned holders awaiting reclaim at lease expiry).",
    "katib_device_lost_total": "Devices removed from custody: probe failures, executor backend errors, chaos revocations.",
    "katib_backend_failover_total": "Whole-backend failovers (every live device lost; the fallback pool was swapped in).",
    # crash-tolerant controller (ISSUE 14, controller/recovery.py) — the
    # ControllerRecovered / LeaseTakenOver / QuiesceTimeout events pair
    # with these series
    "katib_controller_lease_renewals_total": "Heartbeat renewals of the controller's single-writer lease on the state root.",
    "katib_controller_lease_takeover_total": "Times this controller took over an expired or dead-holder lease from a previous incarnation.",
    "katib_controller_lease_age_seconds": "Seconds this controller has continuously held the state-root lease.",
    "katib_controller_lease_fence": "Monotonic fence token of the held lease (increments on every takeover).",
    "katib_recovery_replays_total": "Checkpoint-preserving restarts: load_experiment passes that replayed the recovery journal.",
    "katib_recovery_trials_resubmitted_total": "In-flight trials requeued by a recovery load (one dispatch barrier per restart).",
    "katib_recovery_rows_preserved_total": "Observation rows preserved across controller restarts (at or before the last durable checkpoint).",
    "katib_recovery_rows_truncated_total": "Un-checkpointed observation rows truncated at restart (the resumed stint re-reports them).",
    "katib_recovery_replay_seconds": "Wall-clock of the last recovery replay (journal + truncation + requeue), per experiment.",
    # sharded control plane (ISSUE 15, controller/placement.py +
    # service/httpapi.py) — the ReplicaJoined / ReplicaFailedOver events
    # pair with these series
    "katib_rpc_requests_total": "Wire-protocol requests served, by api.proto service, method and status code.",
    "katib_rpc_latency_seconds": "Wire-protocol request latency, by api.proto service (plus tenant= and method= labels when runtime.wire_tracing is on).",
    # distributed tracing & fleet plane (ISSUE 19, tracing.py + both wire
    # planes) — the TraceContextInvalid warning event pairs with these
    "katib_slo_violations_total": "Wire requests whose latency exceeded the configured per-method objective (runtime.slo_objectives), by tenant and method.",
    "katib_replica_experiments": "Experiments currently placed on each replica (placement leases held).",
    # framed ingest plane (ISSUE 16, service/ingest.py) — the binary
    # observation-streaming sibling of the JSON DBManager wire
    "katib_ingest_frames_total": "Binary observation DATA frames accepted by the framed ingest plane.",
    "katib_ingest_batch_rows": "Observation rows landed per coalesced ingest group commit.",
    "katib_ingest_coalesce_depth": "Frames merged into the most recent coalesced ingest drain.",
    # tenancy plane (ISSUE 17, service/tenancy.py) — per-tenant identity,
    # isolation and quota enforcement on both wire planes
    "katib_tenant_requests_total": "Wire requests admitted under a resolved tenant identity, by tenant.",
    "katib_tenant_denied_total": "Cross-tenant or unauthorized wire requests rejected (403 / ERR frame), by tenant and plane.",
    "katib_tenant_quota_refusals_total": "Experiment admissions refused with a tenant-tagged 429 (admission rate or max-experiments quota).",
    # step-statistics plane (ISSUE 20, runtime/stepstats.py + controller/
    # stepstats.py) — the RetraceStorm / GangStraggler / StepTimeRegression
    # warning events pair with these series
    "katib_step_seconds": "Per-experiment step-time rollup over recent stints, by quantile (p50/p95).",
    "katib_trial_throughput": "Aggregate steps per second per experiment (total steps / total step-seconds over recent stints).",
    "katib_trial_mfu_ratio": "Latest model-FLOPs-utilization per experiment (cost-model FLOPs / achieved FLOP/s over hardware peak).",
    "katib_trial_retraces_total": "Recompiles past the first compile observed by trial stints (JAX compile events), per experiment.",
    "katib_objective_per_device_second": "Best objective divided by accumulated gang device-seconds, per experiment (ROADMAP 3c admission signal).",
}


def _default_help(name: str, kind: str) -> str:
    return _HELP_CATALOG.get(name, f"katib-tpu {kind} {name}.")


# Event-reason catalog: one operator-facing line per reason recorded through
# EventRecorder.event (docs/static-analysis.md KTI302 — the analyzer fails
# the build when a literal reason is emitted without an entry, so every
# event surfaced in /api/events stays look-up-able). Reasons that reach the
# recorder through dynamic sites (trial.current_reason in
# scheduler._record_terminal, the experiment terminal reason in
# experiment._on_completed) are cataloged here too for completeness.
EVENT_CATALOG: Dict[str, str] = {
    # experiment lifecycle
    "ExperimentCreated": "Experiment admitted and persisted.",
    "ExperimentGoalReached": "Objective goal met; experiment succeeded.",
    "ExperimentMaxTrialsReached": "maxTrialCount trials finished; experiment succeeded.",
    "ExperimentMaxFailedTrialsReached": "maxFailedTrialCount exceeded; experiment failed.",
    "ExperimentSuggestionEndReached": "Suggestion algorithm reported search end.",
    "ExperimentSuggestionFailed": "Suggestion service errored; experiment failed.",
    "Succeeded": "Experiment terminal condition (no specific reason recorded).",
    "Failed": "Experiment terminal condition (no specific reason recorded).",
    # trial lifecycle
    "TrialCreated": "Trial admitted to the scheduler queue.",
    "TrialPending": "Trial waiting for its gang device allocation.",
    "TrialRunning": "Trial dispatched onto devices.",
    "TrialSucceeded": "Trial finished with the objective metric available.",
    "TrialFailed": "Trial failed (non-zero exit, exception, or failure condition).",
    "TrialKilled": "Trial killed by early stopping shrink, timeout escalation, or kill().",
    "TrialEarlyStopped": "Early-stopping rules tripped; trial stopped.",
    "MetricsUnavailable": "Trial finished without a usable objective metric.",
    "DuplicateResultReused": "Identical-assignment result copied; workload not re-run.",
    "TrialRestarting": "Failed trial requeued under max_trial_restarts.",
    "TrialResubmitted": "In-flight trial requeued after a controller restart.",
    "TrialLost": "Trial state lost across a controller restart; marked failed.",
    "SchedulerShutdown": "Trial killed because the controller shut down (resumable).",
    # scheduling / packing (PR 1-2)
    "PackFormed": "Compatible trials merged into one vmapped program.",
    "TrialDevicesClamped": "Gang request exceeded machine size; allocation clamped.",
    "TrialPreempted": "Fair-share policy preempted the trial for higher-priority work.",
    "TrialQueueStalled": "Trial pending past runtime.queue_stall_seconds.",
    # telemetry watchdog (PR 5)
    "TrialStalled": "No report() heartbeat past runtime.stall_seconds.",
    "TrialOOMRisk": "Monotonic RSS growth past runtime.oom_risk_fraction of host memory.",
    # semantic admission pre-flight (PR 7, analysis/program.py)
    "PredictedHbmNearCapacity": "Static peak-HBM estimate within the warning fraction of device memory.",
    # AOT compile service (PR 8, katib_tpu/compilesvc)
    "CompileFailed": "AOT compile failed or timed out; fingerprint quarantined, trials compile inline.",
    "BackendInitFailed": "Accelerator backend init/probe failed or hung; device probing disabled for this process.",
    # fused population loops (PR 9, katib_tpu/runtime/population.py)
    "PopulationFused": "Opted-in PBT/ENAS sweep dispatched as one fused on-device population program.",
    # vectorized suggestion plane / transfer HPO (PR 10)
    "WarmStartApplied": "Suggester seeded from completed experiments with a matching search-space signature.",
    # native multi-fidelity search (ISSUE 11, controller/multifidelity.py)
    "RungPaused": "Trial completed its rung budget and paused (checkpoint + observations intact) awaiting a promotion decision.",
    "RungPromoted": "Rung-paused trial resubmitted at the next fidelity, resuming its checkpoint (or from scratch if unusable).",
    "RungPruned": "Rung-paused trial finalized early-stopped: outside the top 1/eta of its rung when the ladder drained.",
    # model-based multi-fidelity (ISSUE 13, controller/multifidelity.py)
    "PromotionBatched": "Same-ladder promotions accumulated under the dwell window were resubmitted as one batch so rung 1+ dispatches as vmapped packs.",
    # supervised device plane (ISSUE 12, controller/deviceplane.py)
    "DeviceLost": "A device left custody (probe failure, heartbeat miss, backend error, or chaos injection); the holding gang preempts.",
    "DeviceLeaseRevoked": "The plane voided a lease: an expired zombie hold was reclaimed into the pool, or a heartbeat-missed holder was cut off.",
    "BackendFailedOver": "Every live device of the backend was lost; the fallback pool was swapped in so the sweep degrades instead of dying.",
    # crash-tolerant controller (ISSUE 14, controller/recovery.py)
    "ControllerRecovered": "A restarted controller replayed the recovery journal and requeued in-flight trials with their checkpointed observation rows preserved.",
    "LeaseTakenOver": "This controller took over the state root's single-writer lease from an expired or dead previous holder (fence token incremented).",
    "QuiesceTimeout": "The scheduler did not quiesce within its deadline after experiment completion; a zombie trial may still hold its gang allocation.",
    # sharded control plane (ISSUE 15, controller/placement.py)
    "ReplicaJoined": "A controller replica registered with the shared root's placement plane and began claiming experiments.",
    "ReplicaFailedOver": "A replica took over a dead or expired peer's experiment placement (fence bumped) and recovered it from the shared root.",
    # multi-tenant service tier (ISSUE 17, service/tenancy.py)
    "AuthDisabled": "Server started with no auth token configured: every wire request is accepted as the break-glass admin identity.",
    "TenantQuotaRefused": "An experiment admission was refused with a tenant-tagged 429 (admission rate or max-experiments quota exceeded).",
    # distributed tracing plane (ISSUE 19, tracing.py + both wire planes)
    "TraceContextInvalid": "A wire request carried a malformed or oversized traceparent (header or frame field); the context was ignored and the request served without it.",
    # step-statistics plane (ISSUE 20, controller/stepstats.py)
    "RetraceStorm": "One stint recompiled more than runtime.retrace_storm_threshold times past the first compile — the train loop is likely shape-unstable and burning its step budget on XLA retraces.",
    "GangStraggler": "A packed/fused member's p95 step time exceeded the gang median by runtime.straggler_ratio — the slowest member is pacing the shared program.",
    "StepTimeRegression": "A resumed/promoted stint's p50 step time exceeded the same trial's prior-stint baseline (persisted perf rows) by runtime.step_regression_ratio.",
}
