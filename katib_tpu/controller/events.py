"""Event recording + Prometheus-style controller metrics.

reference observability surface (SURVEY.md §5):
- K8s Events on every state change (r.recorder.Eventf —
  trial_controller_util.go:66/86/109);
- Prometheus CounterVec/GaugeVec for experiments/trials
  created/succeeded/failed/deleted (experiment/util/prometheus_metrics.go:29-78,
  trial/util/prometheus_metrics.go).

Here: an in-memory (optionally persisted) ring of typed events per
experiment, and a metrics registry rendered in Prometheus text exposition
format (served by katib_tpu.ui.server at /metrics).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple


@dataclass
class Event:
    timestamp: float
    kind: str          # Experiment | Trial
    name: str
    event_type: str    # Normal | Warning
    reason: str
    message: str

    def to_dict(self):
        return {
            "timestamp": self.timestamp,
            "kind": self.kind,
            "name": self.name,
            "type": self.event_type,
            "reason": self.reason,
            "message": self.message,
        }


class EventRecorder:
    def __init__(self, max_events: int = 1000):
        self._lock = threading.Lock()
        self._events: Dict[str, Deque[Event]] = {}
        self.max_events = max_events

    def event(
        self,
        experiment: str,
        kind: str,
        name: str,
        reason: str,
        message: str,
        warning: bool = False,
    ) -> None:
        e = Event(
            timestamp=time.time(),
            kind=kind,
            name=name,
            event_type="Warning" if warning else "Normal",
            reason=reason,
            message=message,
        )
        with self._lock:
            q = self._events.setdefault(experiment, collections.deque(maxlen=self.max_events))
            q.append(e)

    def list(self, experiment: str) -> List[Event]:
        with self._lock:
            return list(self._events.get(experiment, ()))


class MetricsRegistry:
    """Counters/gauges labelled by experiment, Prometheus text format.

    Metric names mirror the reference: katib_experiment_created_total,
    katib_experiment_succeeded_total, katib_experiment_failed_total,
    katib_trial_created_total, katib_trial_succeeded_total,
    katib_trial_failed_total, katib_trial_early_stopped_total, plus running
    gauges (prometheus_metrics.go).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            for (name, labels), value in sorted(self._counters.items()):
                lines.append(f"# TYPE {name} counter") if f"# TYPE {name} counter" not in lines else None
                label_s = ",".join(f'{k}="{v}"' for k, v in labels)
                lines.append(f"{name}{{{label_s}}} {value}")
            for (name, labels), value in sorted(self._gauges.items()):
                lines.append(f"# TYPE {name} gauge") if f"# TYPE {name} gauge" not in lines else None
                label_s = ",".join(f'{k}="{v}"' for k, v in labels)
                lines.append(f"{name}{{{label_s}}} {value}")
        return "\n".join(lines) + "\n"
