"""Supervised device plane — leased, revocable device sets (ISSUE 12).

The bench trajectory's biggest losses were environmental, not algorithmic:
wedged TPU probes burned 150s×N per round, and a device dying mid-sweep
crashed the whole run. Upstream Katib survives this class of failure
because Kubernetes owns device health and reschedules pods; this module is
the single-process equivalent, promoting PR 8's ``bounded_local_devices``
band-aid into a plane that OWNS backend acquisition and device custody:

- **Acquisition** — :func:`acquire_backend` probes the accelerator backend
  with hard timeouts and a cached process-wide verdict (utils/backend.py),
  consulted by the controller, the bench harness, and the telemetry
  sampler; a wedge costs one bounded timeout per process, never minutes
  per call site.
- **Leases** — the scheduler's :class:`~.scheduler.DeviceAllocator` is
  rebuilt on top of :meth:`DevicePlane.acquire` / :meth:`DevicePlane.release`:
  every gang allocation is a :class:`DeviceLease` (holder, grant time,
  heartbeats) that the plane can revoke. A zombie trial's lease (the old
  ``_quarantined`` counter) now EXPIRES: past ``zombie_lease_seconds`` the
  chips return to the pool with a ``DeviceLeaseRevoked`` event instead of
  being counted forever.
- **Device loss as preemption** — :meth:`lose_device` (probe failure,
  heartbeat miss, an executor surfacing a backend ``XlaRuntimeError``, or
  chaos injection) removes the device from custody and notifies the
  scheduler's loss handler, which converts the holding gang into a
  checkpoint-preemption through the existing PR 2/9 freeze/resume
  machinery: observations flushed, trial requeued, resumed bit-identically
  on surviving devices when a checkpoint exists, clean re-run otherwise.
- **Failover** — when the pool drains to nothing (whole backend dead) the
  plane swaps in the next pool of the failover chain (accelerator →
  synthetic CPU slots by default) and emits ``BackendFailedOver``: a sweep
  degrades instead of dying.

Gating: ``runtime.device_plane`` / ``KATIB_TPU_DEVICE_PLANE=0`` removes
the plane entirely — the allocator then runs the legacy free-list path
byte-identically (asserted by tests/test_deviceplane.py).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import chaos
from ..utils.backend import bounded_local_devices, probe_verdict

log = logging.getLogger("katib_tpu.deviceplane")

# lease lifecycle states (docs/device-plane.md)
LEASE_ACTIVE = "active"      # holder is running on the devices
LEASE_ZOMBIE = "zombie"      # holder abandoned (kill-grace expired); expiring
LEASE_REVOKED = "revoked"    # plane reclaimed/voided the lease
LEASE_RELEASED = "released"  # holder returned the devices normally

# Backend-error signatures that mean "the devices died under the program",
# not "the trial's own code failed" — an executor traceback matching one of
# these converts the gang into a preemption instead of a terminal failure.
BACKEND_ERROR_MARKERS = (
    "XlaRuntimeError",
    "DEADLINE_EXCEEDED",
    "failed to legalize operation",
    "Device or slice is unhealthy",
    "device is in an invalid state",
    "TPU initialization failed",
    "Unable to initialize backend",
    "Socket closed",
    "slice health check failed",
)


def is_backend_loss(message: Optional[str]) -> bool:
    """Does this executor failure message carry a backend-death signature?
    Conservative by design: only explicit runtime/transport markers match —
    a trial's own ValueError never converts into a preemption."""
    if not message:
        return False
    return any(marker in message for marker in BACKEND_ERROR_MARKERS)


def acquire_backend(
    timeout_seconds: float = 15.0,
    retries: int = 2,
    events=None,
) -> Tuple[Optional[List[Any]], str]:
    """Health-probed backend acquisition with a hard timeout and cached
    verdict — the plane's front door, shared by the controller bootstrap,
    ``bench.py`` round acquisition, and the probe subprocess. Returns
    ``(devices, diagnosis)``; devices is None when the backend is wedged or
    dead (the verdict is cached, so every later call in this process is an
    immediate None — a wedge can never cost a second timeout)."""
    devices = bounded_local_devices(
        timeout_seconds=timeout_seconds, retries=retries, events=events
    )
    if devices is None:
        return None, (
            "backend probe failed or hung (verdict cached; see the "
            "BackendInitFailed event for the first failure's reason)"
        )
    platform = getattr(devices[0], "platform", "unknown")
    return devices, f"{len(devices)} {platform} device(s)"


@dataclass
class DeviceLease:
    """One revocable custody grant over a device set."""

    lease_id: int
    holder: str                      # dispatch-unit key (first trial's name)
    experiment: str
    devices: List[Any]
    granted_at: float
    state: str = LEASE_ACTIVE
    heartbeats: int = 0
    last_heartbeat: float = 0.0
    expires_at: Optional[float] = None   # zombie reclaim deadline
    lost: List[Any] = field(default_factory=list)  # devices revoked mid-lease
    # chaos schedule attached at grant time (utils/chaos.py)
    chaos_action: Optional[str] = None
    chaos_beats: int = 0
    chaos_pick: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "leaseId": self.lease_id,
            "holder": self.holder,
            "experiment": self.experiment,
            "devices": [str(d) for d in self.devices],
            "grantedAt": self.granted_at,
            "state": self.state,
            "heartbeats": self.heartbeats,
            "lastHeartbeat": self.last_heartbeat,
            "expiresAt": self.expires_at,
            "lost": [str(d) for d in self.lost],
        }


class DevicePlane:
    """Leased device custody + health supervision for one controller.

    Thread-safety: one internal lock guards pool/lease state. The loss and
    kill handlers are invoked WITHOUT the plane lock held (the scheduler's
    handler takes its own lock and calls back into :meth:`release`-adjacent
    paths), so the only lock edge is scheduler→plane.
    """

    def __init__(
        self,
        events=None,
        metrics=None,
        probe_timeout_seconds: float = 15.0,
        reprobe_interval_seconds: float = 0.0,
        zombie_lease_seconds: float = 60.0,
        heartbeat_timeout_seconds: float = 0.0,
        failover: bool = True,
        persist_dir: Optional[str] = None,
        tick_interval_seconds: float = 1.0,
    ) -> None:
        self.events = events
        self.metrics = metrics
        self.probe_timeout_seconds = probe_timeout_seconds
        self.reprobe_interval_seconds = reprobe_interval_seconds
        self.zombie_lease_seconds = zombie_lease_seconds
        self.heartbeat_timeout_seconds = heartbeat_timeout_seconds
        self.failover_enabled = failover
        self.persist_dir = persist_dir
        self.tick_interval_seconds = tick_interval_seconds
        self._lock = threading.Lock()
        self._free: List[Any] = []
        self._backend = "unattached"
        self._leases: Dict[int, DeviceLease] = {}
        self._device_lease: Dict[Any, DeviceLease] = {}
        self._lease_seq = 0
        self._lost_total = 0
        self._failovers = 0
        self._last_probe = 0.0
        self._loss_handler: Optional[Callable[[List[Any], str], None]] = None
        self._kill_handler: Optional[Callable[[str], None]] = None
        self._pool_changed: Optional[Callable[[], None]] = None
        self._shutdown = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        # failover chain: (backend name, pool factory) tried in order when
        # the active pool drains to zero live devices. The default chain is
        # installed by adopt_pool; tests/bench may override.
        self._fallbacks: List[Tuple[str, Callable[[], List[Any]]]] = []

    # -- pool bootstrap ------------------------------------------------------

    def adopt_pool(self, devices: Sequence[Any], backend: str = "external") -> None:
        """Take custody of the scheduler's resolved device pool. The plane
        does NOT probe jax here — pool resolution (explicit devices, or the
        legacy abstract slots) stays in the scheduler so plane-on and
        plane-off controllers see identical pools; jax probing is the
        health layer (tick/acquire_backend), not the allocation source."""
        with self._lock:
            self._free = list(devices)
            self._backend = backend
            if not self._fallbacks:
                # CPU↔TPU↔GPU failover order, degraded to what a single
                # process can actually deliver: whatever backend the pool
                # came from fails over to same-size synthetic CPU slots
                # (in-process trials then run on the default CPU backend).
                n = max(len(self._free), 1)
                self._fallbacks = [
                    ("cpu-fallback", lambda n=n: [f"cpu-slot-{i}" for i in range(n)])
                ]
        self._persist()

    def set_fallbacks(
        self, fallbacks: Sequence[Tuple[str, Callable[[], List[Any]]]]
    ) -> None:
        with self._lock:
            self._fallbacks = list(fallbacks)

    def set_loss_handler(self, fn: Callable[[List[Any], str], None]) -> None:
        """``fn(devices, reason)`` — called (no plane lock held) when
        devices leave custody while leased; the scheduler converts the
        holding gang into a checkpoint-preemption."""
        self._loss_handler = fn

    def set_kill_handler(self, fn: Callable[[str], None]) -> None:
        """``fn(holder)`` — chaos process-kill injection target."""
        self._kill_handler = fn

    def set_pool_changed_handler(self, fn: Callable[[], None]) -> None:
        """``fn()`` — called after devices re-enter the pool outside the
        normal release path (zombie reclaim, lease revocation, failover),
        so the scheduler re-runs its dispatch pass for waiting gangs."""
        self._pool_changed = fn

    def _notify_pool_changed(self) -> None:
        fn = self._pool_changed
        if fn is not None:
            try:
                fn()
            except Exception:
                log.exception("pool-changed handler failed")

    # -- allocator surface (DeviceAllocator delegates here) ------------------

    def acquire(self, n: int, holder: str = "", experiment: str = "") -> Optional[List[Any]]:
        with self._lock:
            if n > len(self._free):
                return None
            taken, self._free = self._free[:n], self._free[n:]
            self._lease_seq += 1
            lease = DeviceLease(
                lease_id=self._lease_seq,
                holder=holder,
                experiment=experiment,
                devices=list(taken),
                granted_at=time.time(),
                last_heartbeat=time.time(),
            )
            plan = chaos.active()
            if plan is not None:
                scheduled = plan.next_grant()
                if scheduled is not None:
                    lease.chaos_action, lease.chaos_beats, lease.chaos_pick = scheduled
            self._leases[lease.lease_id] = lease
            for d in taken:
                self._device_lease[d] = lease
        if self.metrics is not None:
            self.metrics.inc("katib_device_lease_granted_total")
            self._gauge_leases()
        self._persist()
        return taken

    def release(self, devices: Sequence[Any]) -> List[Any]:
        """Return a gang's devices to the pool. Only devices still in the
        lease's custody come back — revoked/lost members stay gone, and a
        lease the plane already reclaimed (zombie expiry) is a no-op, so
        the late-exiting zombie thread can never double-free chips."""
        returned: List[Any] = []
        with self._lock:
            for d in devices:
                lease = self._device_lease.pop(d, None)
                if lease is None:
                    continue  # reclaimed or lost while leased
                if d not in lease.lost:
                    self._free.append(d)
                    returned.append(d)
                if lease.state in (LEASE_ACTIVE, LEASE_ZOMBIE):
                    lease.state = LEASE_RELEASED
            self._prune_locked()
        if returned and self.metrics is not None:
            self._gauge_leases()
        self._persist()
        return returned

    TERMINAL_LEASES_KEPT = 256

    def _prune_locked(self) -> None:
        """Bound the lease registry: terminal leases beyond the newest
        TERMINAL_LEASES_KEPT are dropped (they exist only for the CLI /
        snapshot history). Caller holds the plane lock."""
        terminal = sorted(
            lid
            for lid, l in self._leases.items()
            if l.state in (LEASE_RELEASED, LEASE_REVOKED)
        )
        excess = max(len(terminal) - self.TERMINAL_LEASES_KEPT, 0)
        for lid in terminal[:excess]:
            del self._leases[lid]

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def total(self) -> int:
        """Live devices in custody: free + leased-and-not-lost."""
        with self._lock:
            leased = sum(
                1
                for d, lease in self._device_lease.items()
                if d not in lease.lost
            )
            return len(self._free) + leased

    @property
    def backend(self) -> str:
        with self._lock:
            return self._backend

    # -- zombie leases (the _quarantined reclaim path) -----------------------

    def mark_zombie(self, devices: Sequence[Any], holder: str = "") -> None:
        """An abandoned trial still references these chips: flag its lease
        ZOMBIE with a reclaim deadline. If the worker thread exits first,
        the normal release path runs; past the deadline the plane reclaims
        the chips itself (the old ``_quarantined`` counter leak)."""
        deadline = time.time() + max(self.zombie_lease_seconds, 0.0)
        with self._lock:
            for d in devices:
                lease = self._device_lease.get(d)
                if lease is not None and lease.state == LEASE_ACTIVE:
                    lease.state = LEASE_ZOMBIE
                    lease.expires_at = deadline
        self._persist()

    def zombie_device_count(self) -> int:
        with self._lock:
            return sum(
                len([d for d in l.devices if d not in l.lost])
                for l in self._leases.values()
                if l.state == LEASE_ZOMBIE
            )

    def _reclaim_expired_locked(self, now: float) -> List[DeviceLease]:
        expired = [
            l
            for l in self._leases.values()
            if l.state == LEASE_ZOMBIE
            and l.expires_at is not None
            and now >= l.expires_at
        ]
        for lease in expired:
            lease.state = LEASE_REVOKED
            for d in lease.devices:
                if self._device_lease.get(d) is lease:
                    del self._device_lease[d]
                    if d not in lease.lost:
                        self._free.append(d)
        return expired

    # -- device loss ---------------------------------------------------------

    def lose_device(self, device: Any, reason: str = "injected") -> bool:
        """Remove one device from custody (probe failure, chaos injection,
        executor backend error). A free device just leaves the pool; a
        leased device additionally notifies the loss handler so the holding
        gang preempts. Returns False when the device is unknown (already
        lost, or from a failed-over pool)."""
        handler_args: Optional[Tuple[List[Any], str]] = None
        with self._lock:
            lease = self._device_lease.get(device)
            if lease is not None:
                if device in lease.lost:
                    return False
                lease.lost.append(device)
                handler_args = ([device], reason)
            elif device in self._free:
                self._free.remove(device)
            else:
                return False
            self._lost_total += 1
        log.warning("device %s lost (%s)", device, reason)
        if self.events is not None:
            holder = lease.holder if lease is not None else "(free pool)"
            self.events.event(
                lease.experiment if lease is not None else "",
                "Controller", "deviceplane", "DeviceLost",
                f"device {device} lost ({reason}); held by {holder}",
                warning=True,
            )
        if self.metrics is not None:
            self.metrics.inc("katib_device_lost_total")
            self._gauge_leases()
        if handler_args is not None and self._loss_handler is not None:
            try:
                self._loss_handler(*handler_args)
            except Exception:
                log.exception("device-loss handler failed")
        self._maybe_failover()
        self._persist()
        return True

    def report_executor_failure(self, holder: str, devices: Sequence[Any]) -> bool:
        """An executor surfaced a backend-death signature for this gang:
        mark every still-held device of the allocation lost. Returns True
        when at least one device was in custody (the scheduler then
        converts the failure into a preemption). The loss handler is NOT
        invoked — the failing gang is already unwinding; marking the
        devices keeps them out of the pool at release."""
        lost_any = False
        with self._lock:
            for d in devices:
                lease = self._device_lease.get(d)
                if lease is not None and d not in lease.lost:
                    lease.lost.append(d)
                    self._lost_total += 1
                    lost_any = True
        if lost_any:
            if self.events is not None:
                self.events.event(
                    "", "Controller", "deviceplane", "DeviceLost",
                    f"backend error under {holder}: {len(list(devices))} "
                    "device(s) of its gang marked lost; gang converts to a "
                    "checkpoint-preemption",
                    warning=True,
                )
            if self.metrics is not None:
                self.metrics.inc(
                    "katib_device_lost_total", value=float(len(list(devices)))
                )
                self._gauge_leases()
            self._maybe_failover()
            self._persist()
        return lost_any

    def _maybe_failover(self) -> None:
        """When no live device remains (free or leased), swap in the next
        pool of the failover chain so pending work degrades instead of
        starving forever."""
        if not self.failover_enabled:
            return
        with self._lock:
            live = len(self._free) + sum(
                1 for d, l in self._device_lease.items() if d not in l.lost
            )
            if live > 0 or not self._fallbacks:
                return
            name, factory = self._fallbacks.pop(0)
            try:
                fresh = list(factory())
            except Exception:
                log.exception("failover pool factory for %r failed", name)
                return
            old = self._backend
            self._backend = name
            self._free.extend(fresh)
            self._failovers += 1
        log.warning(
            "backend %s lost every device; failed over to %s (%d device(s))",
            old, name, len(fresh),
        )
        if self.events is not None:
            self.events.event(
                "", "Controller", "deviceplane", "BackendFailedOver",
                f"backend {old} lost every device; failed over to {name} "
                f"({len(fresh)} device(s)) — the sweep degrades instead of dying",
                warning=True,
            )
        if self.metrics is not None:
            self.metrics.inc("katib_backend_failover_total")
            self._gauge_leases()
        self._notify_pool_changed()

    # -- heartbeats + chaos triggers -----------------------------------------

    def heartbeat(self, holder: str) -> None:
        """Lease liveness tick, wired into ctx.report via the scheduler.
        Chaos faults scheduled on this lease (revoke/kill after its N-th
        heartbeat) fire here — deterministically, on the holder's own
        report cadence, never on wall clock."""
        fire: Optional[Tuple[str, DeviceLease]] = None
        with self._lock:
            lease = next(
                (
                    l
                    for l in self._leases.values()
                    if l.holder == holder and l.state == LEASE_ACTIVE
                ),
                None,
            )
            if lease is None:
                return
            lease.heartbeats += 1
            lease.last_heartbeat = time.time()
            if lease.chaos_action is not None and lease.heartbeats >= lease.chaos_beats:
                fire = (lease.chaos_action, lease)
                lease.chaos_action = None
        if fire is None:
            return
        action, lease = fire
        if action == chaos.ACTION_REVOKE:
            live = [d for d in lease.devices if d not in lease.lost]
            if live:
                self.lose_device(
                    live[lease.chaos_pick % len(live)], reason="chaos revocation"
                )
        elif action == chaos.ACTION_KILL and self._kill_handler is not None:
            try:
                self._kill_handler(lease.holder)
            except Exception:
                log.exception("chaos kill handler failed")

    # -- supervision ---------------------------------------------------------

    def start(self) -> None:
        if self._supervisor is not None:
            return
        self._supervisor = threading.Thread(
            target=self._run_supervisor, name="deviceplane-supervisor", daemon=True
        )
        self._supervisor.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
            self._supervisor = None

    def _run_supervisor(self) -> None:
        while not self._shutdown.wait(self.tick_interval_seconds):
            try:
                self.tick()
            except Exception:
                log.exception("device plane tick failed")

    def tick(self, now: Optional[float] = None) -> None:
        """One supervision pass: reclaim expired zombie leases, revoke
        heartbeat-missed leases (when the knob is on), and re-probe the
        backend on its interval. Cheap when nothing is due — the default
        1s cadence costs a lock acquisition."""
        now = time.time() if now is None else now
        with self._lock:
            reclaimed = self._reclaim_expired_locked(now)
            missed: List[DeviceLease] = []
            if self.heartbeat_timeout_seconds > 0:
                missed = [
                    l
                    for l in self._leases.values()
                    if l.state == LEASE_ACTIVE
                    and now - l.last_heartbeat > self.heartbeat_timeout_seconds
                ]
        for lease in reclaimed:
            live = [d for d in lease.devices if d not in lease.lost]
            log.warning(
                "zombie lease %d (%s) expired; reclaimed %d device(s)",
                lease.lease_id, lease.holder, len(live),
            )
            if self.events is not None:
                self.events.event(
                    lease.experiment, "Controller", "deviceplane",
                    "DeviceLeaseRevoked",
                    f"zombie lease of {lease.holder} expired after "
                    f"{self.zombie_lease_seconds:.0f}s; {len(live)} device(s) "
                    "reclaimed into the pool",
                    warning=True,
                )
            if self.metrics is not None:
                self.metrics.inc("katib_device_lease_revoked_total")
        for lease in missed:
            self._revoke_lease(lease, reason="lease heartbeat missed")
        if (
            self.reprobe_interval_seconds > 0
            and now - self._last_probe >= self.reprobe_interval_seconds
        ):
            self._last_probe = now
            self._reprobe()
        if reclaimed or missed:
            if self.metrics is not None:
                self._gauge_leases()
            self._notify_pool_changed()
        # heartbeats don't persist (they are per-report hot path); the tick
        # refreshes the offline snapshot once per interval instead
        self._persist()

    def _revoke_lease(self, lease: DeviceLease, reason: str) -> None:
        """Void an ACTIVE lease: its devices count as lost to the holder
        (the loss handler preempts the gang) but return to the pool — the
        hardware is presumed fine, the HOLDER is presumed gone."""
        with self._lock:
            if lease.state != LEASE_ACTIVE:
                return
            lease.state = LEASE_REVOKED
            recovered = []
            for d in lease.devices:
                if self._device_lease.get(d) is lease:
                    del self._device_lease[d]
                    if d not in lease.lost:
                        self._free.append(d)
                        recovered.append(d)
        if self.events is not None:
            self.events.event(
                lease.experiment, "Controller", "deviceplane",
                "DeviceLeaseRevoked",
                f"lease of {lease.holder} revoked ({reason}); "
                f"{len(recovered)} device(s) returned to the pool",
                warning=True,
            )
        if self.metrics is not None:
            self.metrics.inc("katib_device_lease_revoked_total")
        if self._loss_handler is not None:
            try:
                self._loss_handler(list(lease.devices), reason)
            except Exception:
                log.exception("device-loss handler failed")

    def _reprobe(self) -> None:
        """Periodic backend health re-probe. Only meaningful when the pool
        is real accelerator devices AND a probe already succeeded once: a
        previously-healthy backend whose probe now fails means every pooled
        device is gone — lose them all (which triggers failover)."""
        if probe_verdict() is not True:
            return  # never probed / already known dead: nothing to re-check
        devices, _diag = acquire_backend(
            timeout_seconds=self.probe_timeout_seconds, events=self.events
        )
        if devices is not None:
            return
        with self._lock:
            pooled = list(self._free) + [
                d for d, l in self._device_lease.items() if d not in l.lost
            ]
        for d in pooled:
            if not isinstance(d, (int, str)):  # abstract slots don't die with jax
                self.lose_device(d, reason="backend re-probe failed")

    # -- observability -------------------------------------------------------

    def _gauge_leases(self) -> None:
        with self._lock:
            active = sum(1 for l in self._leases.values() if l.state == LEASE_ACTIVE)
            zombies = sum(1 for l in self._leases.values() if l.state == LEASE_ZOMBIE)
        self.metrics.set_gauge("katib_device_lease_active", float(active))
        self.metrics.set_gauge("katib_device_lease_zombie", float(zombies))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            leases = [l.to_dict() for l in self._leases.values()]
            return {
                "backend": self._backend,
                "probeVerdict": {True: "healthy", False: "failed", None: "unprobed"}[
                    probe_verdict()
                ],
                "free": [str(d) for d in self._free],
                "freeCount": len(self._free),
                "lostTotal": self._lost_total,
                "failovers": self._failovers,
                "zombieLeaseSeconds": self.zombie_lease_seconds,
                "heartbeatTimeoutSeconds": self.heartbeat_timeout_seconds,
                "leases": sorted(leases, key=lambda l: l["leaseId"]),
            }

    STATE_FILE = "state.json"

    def _persist(self) -> None:
        """Atomic snapshot under <root>/deviceplane/ so `katib-tpu devices`
        reads lease/health state offline (same pattern as the compile
        registry). Best-effort: persistence must never fail an allocation."""
        if not self.persist_dir:
            return
        try:
            os.makedirs(self.persist_dir, exist_ok=True)
            path = os.path.join(self.persist_dir, self.STATE_FILE)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            log.debug("device plane snapshot persist failed", exc_info=True)
