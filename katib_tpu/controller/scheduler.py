"""Trial scheduler — gang device allocation + trial lifecycle supervision.

TPU-native replacement for the reference's trial controller + kube-scheduler
pair (pkg/controller.v1beta1/trial/trial_controller.go): instead of creating
K8s jobs and mapping their conditions back via GJSON, the scheduler

- gang-allocates devices: a trial asks for ``resources.num_devices`` TPU
  chips and is dispatched only when that many are free (all-or-nothing, like
  a gang-scheduled JAXJob; SURVEY.md §7 layer 4);
- runs the trial via an executor on a worker thread;
- on completion folds the observation log into the trial record
  (UpdateTrialStatusObservation, trial_controller_util.go:124-217) and applies
  the success/failure/metrics-unavailable classification
  (trial_controller_util.go:42-122);
- pushes a completion event that wakes the experiment controller — replacing
  K8s watch events and the 1-second metrics requeue
  (trial_controller.go:182-185) with direct event delivery.

Dispatch order is governed by the fair-share policy (controller/fairshare.py):
priority classes, per-experiment device quotas, deficit-weighted fair-share
ordering with aging, backfill around a blocked gang's reservation, and
checkpoint-based preemption of lower-priority running trials. When no
experiment sets any fair-share knob, the legacy arrival-order path runs
unchanged.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..api.spec import CollectorKind, ObjectiveType, UNAVAILABLE_METRIC_VALUE
from ..api.status import Experiment, Trial, TrialCondition
from ..db.state import ExperimentStateStore
from ..db.store import ObservationStore
from ..runtime.context import TrialContext
from ..runtime.metrics import EarlyStoppingMonitor, MetricsReporter
from .executor import (
    ExecutionResult,
    InProcessExecutor,
    MultiHostExecutor,
    SubprocessExecutor,
    TrialExecution,
    TrialOutcome,
)

log = logging.getLogger("katib_tpu.scheduler")


@dataclass
class TrialEvent:
    experiment_name: str
    trial_name: str
    condition: TrialCondition


class DeviceAllocator:
    """All-or-nothing chip allocator.

    Legacy shape (``plane=None``): a fixed free list — acquisition and
    release shuffle devices between the list and the holders, and the pool
    can never change size. With a supervised device plane attached
    (controller/deviceplane.py), every gang allocation is a revocable
    LEASE: the plane tracks holders and heartbeats, reclaims zombie leases
    on expiry, removes lost devices from custody, and swaps in a failover
    pool when the backend dies — so ``total``/``free_count`` are live
    views, not constants. The legacy path is byte-identical when no plane
    is attached (KATIB_TPU_DEVICE_PLANE=0)."""

    def __init__(self, devices: Sequence[Any], plane=None):
        self._lock = threading.Lock()
        self._plane = plane
        if plane is not None:
            plane.adopt_pool(devices)
            self._free = []
            self._total = len(list(devices))
        else:
            self._free: List[Any] = list(devices)
            self._total = len(self._free)

    def acquire(
        self, n: int, holder: str = "", experiment: str = ""
    ) -> Optional[List[Any]]:
        if self._plane is not None:
            return self._plane.acquire(n, holder=holder, experiment=experiment)
        with self._lock:
            if n > len(self._free):
                return None
            taken, self._free = self._free[:n], self._free[n:]
            return taken

    def release(self, devices: Sequence[Any]) -> None:
        if self._plane is not None:
            self._plane.release(devices)
            return
        with self._lock:
            self._free.extend(devices)

    @property
    def free_count(self) -> int:
        if self._plane is not None:
            return self._plane.free_count
        with self._lock:
            return len(self._free)

    @property
    def total(self) -> int:
        if self._plane is not None:
            return self._plane.total
        return self._total


class TrialScheduler:
    def __init__(
        self,
        state: ExperimentStateStore,
        obs_store: ObservationStore,
        devices: Optional[Sequence[Any]] = None,
        db_path: Optional[str] = None,
        workdir_root: Optional[str] = None,
        events=None,
        metrics=None,
        trial_timeout: Optional[float] = None,
        max_trial_restarts: int = 0,
        poll_interval: Optional[float] = None,
        devices_per_host: Optional[int] = None,
        queue_stall_seconds: float = 120.0,
        aging_seconds: float = 60.0,
        preemption_grace_seconds: float = 30.0,
        tracer=None,
        telemetry=None,
        compile_service=None,
        compile_gate_seconds: float = 0.0,
        fused_population: bool = True,
        population_chunk_generations: int = 16,
        population_stream: bool = False,
        suggestion_prefetch: Optional[Callable[[str], None]] = None,
        multifidelity=None,
        device_plane=None,
        journal=None,
        step_stats=None,
    ):
        from .fairshare import FairSharePolicy
        from ..tracing import install_log_context

        install_log_context()  # experiment=/trial=/trace_id= log stamping
        self.recorder = events
        self.metrics_registry = metrics
        self.tracer = tracer  # katib_tpu.tracing.Tracer (None = no tracing)
        self.telemetry = telemetry  # telemetry.ResourceSampler (None = off)
        # async suggestion pipeline hook (ISSUE 10): called with the
        # experiment name whenever a trial reaches a terminal condition, so
        # the SuggestionService can precompute the next batch before the
        # reconcile loop consults it
        self.suggestion_prefetch = suggestion_prefetch
        self._queue_spans: Dict[str, Any] = {}  # trial -> open queue_wait span
        if devices is None:
            devices = list(range(8))  # abstract slots when JAX not involved
        if devices_per_host:
            devices = list(devices)[:devices_per_host]
        # -- supervised device plane (controller/deviceplane.py) -------------
        # None = disabled: the allocator below runs the legacy free-list
        # path byte-identically and every consult is one `is None` check
        self.device_plane = device_plane
        self.allocator = DeviceAllocator(devices, plane=device_plane)
        if device_plane is not None:
            # device loss (probe failure, heartbeat miss, chaos revocation)
            # converts the holding gang into a checkpoint-preemption; pool
            # changes (zombie reclaim, failover) re-run the dispatch pass
            device_plane.set_loss_handler(self._on_devices_lost)
            device_plane.set_kill_handler(self._chaos_kill_holder)
            device_plane.set_pool_changed_handler(self._on_pool_changed)
        self._unit_devices: Dict[str, List[Any]] = {}  # unit key -> gang devices
        self.state = state
        self.obs_store = obs_store
        self.events: "queue.Queue[TrialEvent]" = queue.Queue()
        self.workdir_root = workdir_root
        self.trial_timeout = trial_timeout
        self.max_trial_restarts = max_trial_restarts
        self._restarts: Dict[str, int] = {}
        self._in_process = InProcessExecutor(obs_store)
        self._subprocess = SubprocessExecutor(obs_store, db_path=db_path)
        self._multihost = MultiHostExecutor(obs_store, db_path=db_path)
        if poll_interval:
            self._subprocess.POLL_INTERVAL = poll_interval
            self._multihost.POLL_INTERVAL = poll_interval
        self._handles: Dict[str, TrialExecution] = {}
        self._pending: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._waiting: List = []  # trials waiting for devices
        self._threads: List[threading.Thread] = []
        self._checkpoint_dirs: Dict[str, str] = {}
        self._quarantined = 0  # devices held by abandoned zombie trials
        self._shutdown = threading.Event()
        self._intentional_kills: set = set()  # kill() targets, vs shutdown kills
        self._dispatch_paused = 0  # dispatch_barrier depth (batch submits)
        # -- fair-share scheduling state (controller/fairshare.py) -----------
        self.queue_stall_seconds = queue_stall_seconds
        self.preemption_grace_seconds = preemption_grace_seconds
        self._policy = FairSharePolicy(aging_seconds=aging_seconds)
        self._seq_counter = 0                      # arrival order for the queue
        self._enqueue_seq: Dict[str, int] = {}     # trial -> arrival seq
        self._enqueued_at: Dict[str, float] = {}   # trial -> pending since
        self._stall_emitted: set = set()           # TrialQueueStalled once/stint
        self._usage: Dict[str, int] = {}           # experiment -> devices held
        self._running: Dict[str, Any] = {}         # unit key -> RunningUnit
        self._preempting: set = set()              # trials signalled to preempt
        self._last_checkpoint: Dict[str, float] = {}  # trial -> last ckpt save
        self._gauged_experiments: set = set()      # queue gauges to zero out
        # backfill reservation: the first blocked unit in policy order
        # earmarks every chip released while it stays blocked (its credits);
        # backfill may only use free chips beyond the credits
        self._head_key: Optional[str] = None
        self._head_credits = 0
        # -- AOT compile service (compilesvc/service.py) ---------------------
        # None = disabled: every consult below is one `is None` check and
        # dispatch is byte-identical to the legacy path
        self.compile_service = compile_service
        self.compile_gate_seconds = compile_gate_seconds
        # -- fused population loops (runtime/population.py) ------------------
        # off, or for any pack that is not an opted-in fused sweep, the
        # PackedTrialExecutor path below is byte-identical to before
        self.fused_population = fused_population
        self.population_chunk_generations = population_chunk_generations
        self.population_stream = population_stream
        # -- multi-fidelity engine (controller/multifidelity.py) -------------
        # None = disabled: every consult below is one `is None` check and
        # trial finalization is byte-identical to the legacy path; with an
        # engine attached only `algorithm: asha` experiments use it
        self.multifidelity = multifidelity
        # -- recovery journal (controller/recovery.py, ISSUE 14) -------------
        # None = disabled: dispatch and terminal transitions leave no intent
        # records and every consult below is one `is None` check
        self.journal = journal
        # -- step-statistics plane (controller/stepstats.py, ISSUE 20) -------
        # None = disabled: no clocks are bound to contexts, no perf rows are
        # written, and every consult below is one `is None` check
        self.step_stats = step_stats
        self._gate_since: Dict[Any, float] = {}  # group key -> hold start
        self._gate_held: Dict[str, float] = {}   # trial -> hold start (spans)
        self._gate_timer_live = False            # one wake timer per hold
        if compile_service is not None:
            # a program turning warm (or failing) re-runs the dispatch pass;
            # the service notifies with NO service lock held, so the only
            # lock edge is scheduler->service (from the dispatch walk)
            compile_service.add_listener(self._on_compile_transition)

    # -- submission ----------------------------------------------------------

    LINEAGE_LABEL = "checkpoint-lineage"

    def _tr(self):
        """The active tracer, or None when tracing is off — every
        instrumentation site guards on this one cheap check."""
        t = self.tracer
        return t if (t is not None and t.enabled) else None

    def _tm(self):
        """The active resource sampler, or None when telemetry is off —
        same one-boolean-check contract as _tr()."""
        t = self.telemetry
        return t if (t is not None and t.enabled) else None

    def _cs(self):
        """The active compile service, or None when disabled — same
        one-check contract as _tr()/_tm()."""
        s = self.compile_service
        return s if (s is not None and s.active) else None

    def _mf(self):
        """The multi-fidelity engine, or None when runtime.multifidelity is
        off — same one-check contract as _tr()/_tm()/_cs()."""
        return self.multifidelity

    def _dp(self):
        """The supervised device plane, or None when runtime.device_plane
        is off — same one-check contract as _tr()/_tm()/_cs()/_mf()."""
        return self.device_plane

    def _on_devices_lost(self, devices: Sequence[Any], reason: str) -> None:
        """Device-plane loss handler (no plane lock held): every running
        unit holding a lost device converts into a checkpoint-preemption —
        the cooperative signal first (victims checkpoint-and-yield at their
        next report through the PR 2/9 freeze machinery), the grace-window
        kill as escalation. Requeued members resume from their last
        checkpoint on the surviving devices bit-identically, or re-run
        clean without one — exactly the fair-share preemption contract."""
        lost = set(devices)
        victims = []
        with self._lock:
            for key, unit in self._running.items():
                held = self._unit_devices.get(key, ())
                if any(d in lost for d in held):
                    unit.preempt_signaled = True
                    self._preempting.update(unit.trial_names)
                    victims.append(unit)
        for unit in victims:
            log.warning(
                "device loss (%s): preempting %s to requeue on surviving "
                "devices", reason, ",".join(unit.trial_names),
            )
            for h in unit.handles:
                h.preempt()
            if self.preemption_grace_seconds:
                timer = threading.Timer(
                    self.preemption_grace_seconds,
                    lambda hs=list(unit.handles): [h.kill() for h in hs],
                )
                timer.daemon = True
                timer.start()
        if not self._shutdown.is_set():
            self._dispatch()

    def _chaos_kill_holder(self, holder: str) -> None:
        """Chaos process-kill injection (utils/chaos.py): hard-kill the
        holding unit, but through the preemption bookkeeping — a chaos
        kill models an external death, and the trial must requeue and
        recover exactly like a device-loss victim, not count as a
        deliberate kill()."""
        with self._lock:
            unit = self._running.get(holder)
            if unit is None:
                return
            unit.preempt_signaled = True
            self._preempting.update(unit.trial_names)
            handles = list(unit.handles)
        log.warning("chaos kill injected on %s", holder)
        for h in handles:
            h.kill()

    def _on_pool_changed(self) -> None:
        """Plane hook: devices re-entered the pool outside the normal
        release path (zombie-lease reclaim, revocation, failover) — run a
        dispatch pass so waiting gangs pick them up."""
        if not self._shutdown.is_set():
            self._dispatch()

    def _on_compile_transition(self, key) -> None:
        """CompileService listener (worker thread, no service lock held): a
        group turned warm or was quarantined — re-run the dispatch pass so
        gate-held units start (or fall back to inline compilation)."""
        if not self._shutdown.is_set():
            self._dispatch()

    def _trace_end_trial(self, exp_name: str, trial: Trial) -> None:
        """End the trial's root span once it is terminal (idempotent).
        Called AFTER all child spans closed so parents outlive children."""
        tr = self._tr()
        if tr is not None and trial.is_terminal:
            attrs = {}
            if self.workdir_root:
                import os
                # deep-profile linkage (runtime/profiling.py): when the trial
                # captured xplane dumps, stamp their location on the root
                # span so `katib-tpu trace <exp>` shows which trials have a
                # profiler trace behind their spans. _record_terminal's
                # retainRun cleanup ran already, so the stamp only lands
                # when the dumps actually survive on disk (retained,
                # failed/killed, or rung-paused workdirs).
                from ..runtime.profiling import list_profile_artifacts

                workdir = os.path.join(self.workdir_root, exp_name, trial.name)
                if list_profile_artifacts(workdir):
                    from ..runtime.profiling import PROFILE_DIRNAME

                    attrs["profileDir"] = os.path.join(workdir, PROFILE_DIRNAME)
            tr.end_trial(
                exp_name, trial.name,
                outcome=trial.condition.value, reason=trial.current_reason,
                **attrs,
            )

    def submit(
        self,
        exp: Experiment,
        trial: Trial,
        checkpoint_dir: Optional[str] = None,
        dispatch: bool = True,
    ) -> None:
        """Queue a trial. ``dispatch=False`` defers the dispatch pass so a
        caller submitting a batch (one reconcile's worth of suggestions) can
        queue them all first and call :meth:`dispatch` once — without this,
        the first packable trial of a batch would start solo before its
        pack-mates arrive (controller/packing.py)."""
        if checkpoint_dir:
            # Persisted marker (the _checkpoint_dirs entry is transient —
            # popped on start): this trial trains FROM a parent checkpoint,
            # so its metrics reflect inherited training, and duplicate-reuse
            # must never treat it as a from-scratch result for the same
            # assignments — in either direction (advisor round-4 finding:
            # the old guard only blocked lineage trials as reuse TARGETS).
            trial.labels[self.LINEAGE_LABEL] = "1"
        tr = self._tr()
        admission = None
        if tr is not None:
            # one trace per trial: the controller may already have begun it
            # at suggestion time; direct submits (resume, tests) begin here
            root = tr.begin_trial(exp.name, trial.name)
            admission = tr.start_span(
                "admission", exp.name, root.trace_id, root.span_id,
                attrs={"lineage": bool(checkpoint_dir)},
            )
        trial.set_condition(TrialCondition.PENDING, "TrialPending", "waiting for devices")
        self.state.update_trial(trial)
        if self.metrics_registry is not None:
            self.metrics_registry.inc("katib_trial_created_total", experiment=exp.name)
        if self.recorder is not None:
            self.recorder.event(exp.name, "Trial", trial.name, "TrialCreated", "Trial is created")
        if checkpoint_dir:
            with self._lock:
                self._checkpoint_dirs[trial.name] = checkpoint_dir
        elif (
            # the persisted label, not the transient checkpoint_dir arg: a
            # resumed lineage trial can be resubmitted with
            # checkpoint_dir=None (experiment.py resume path swallows
            # _checkpoint_dir_for failures) and must still never consume a
            # from-scratch result
            not trial.labels.get(self.LINEAGE_LABEL)
            and exp.spec.reuse_duplicate_results
            and self._reuse_duplicate(exp, trial)
        ):
            # finalized from a prior identical-assignment success; never
            # reused for checkpoint-lineage trials (PBT exploit/explore
            # trains FROM a parent checkpoint — same params, different run)
            if tr is not None:
                tr.end_span(admission, reused=True)
                self._trace_end_trial(exp.name, trial)
            return
        if tr is not None:
            tr.end_span(admission)
        cs = self._cs()
        if cs is not None:
            # AOT compile request for this trial's dispatch group — dict hit
            # after the first trial of a group; the compile itself runs on
            # the service's worker pool, never on this thread
            trace_ctx = None
            if tr is not None:
                root = tr.trial_root(exp.name, trial.name)
                if root is not None:
                    trace_ctx = (root.trace_id, root.span_id)
            try:
                cs.request(exp, trial, trace=trace_ctx)
            except Exception:
                log.debug("compile service request failed", exc_info=True)
        with self._lock:
            self._stamp_enqueue(exp, trial)
            self._waiting.append((exp, trial))
        if dispatch:
            self._dispatch()

    def _stamp_enqueue(self, exp: Experiment, trial: Trial) -> None:
        """Record arrival order + pending-since for the fair-share queue;
        caller holds the scheduler lock."""
        self._seq_counter += 1
        self._enqueue_seq[trial.name] = self._seq_counter
        self._enqueued_at[trial.name] = time.time()
        tr = self._tr()
        if tr is not None:
            root = tr.trial_root(exp.name, trial.name)
            if root is not None:
                self._queue_spans[trial.name] = tr.start_span(
                    "queue_wait", exp.name, root.trace_id, root.span_id
                )

    def _clear_enqueue(self, trial_name: str, experiment: str = "") -> None:
        """Drop a trial's queue bookkeeping (dispatched or killed while
        pending); caller holds the scheduler lock."""
        self._enqueue_seq.pop(trial_name, None)
        self._enqueued_at.pop(trial_name, None)
        span = self._queue_spans.pop(trial_name, None)
        gated_since = self._gate_held.pop(trial_name, None)
        if span is not None:
            tr = self._tr()
            if tr is not None:
                # stall flag from PR 2's queue bookkeeping: was this wait
                # long enough that TrialQueueStalled fired for it?
                attrs: Dict[str, Any] = {
                    "stalled": trial_name in self._stall_emitted
                }
                now = time.time()
                if gated_since is not None:
                    # Perfetto distinction: "waiting for chips" vs "waiting
                    # for XLA" — this wait was (partly) the compile gate
                    attrs["compileGated"] = True
                    attrs["compileGateSeconds"] = round(now - gated_since, 3)
                    if experiment:
                        tr.record_span(
                            "compile_gate", experiment, span.trace_id,
                            span.parent_id, start=gated_since, end=now,
                        )
                tr.end_span(span, **attrs)
        self._stall_emitted.discard(trial_name)

    def dispatch(self) -> None:
        """Start every waiting trial/pack whose gang allocation fits (the
        public form of the internal dispatch pass, for deferred submits)."""
        self._dispatch()

    def dispatch_barrier(self):
        """Context manager making a batch submission atomic with respect to
        dispatch: passes triggered while the barrier is held (a compile
        finishing in the service, a concurrent trial releasing its gang)
        return immediately, and one pass runs at exit. Without this, a
        dispatch landing between a batch's submit() calls sees a PARTIAL
        batch — which split a fused population sweep into two smaller
        packs, each running a full independent sweep (doubled population
        rows, wrong population semantics), and starts packable trials solo
        before their pack-mates arrive."""
        import contextlib

        @contextlib.contextmanager
        def barrier():
            with self._lock:
                self._dispatch_paused += 1
            try:
                yield
            finally:
                with self._lock:
                    self._dispatch_paused -= 1
                self._dispatch()

        return barrier()

    def _reuse_duplicate(self, exp: Experiment, trial: Trial) -> bool:
        """Opt-in duplicate-result reuse (spec.reuse_duplicate_results): if a
        Succeeded trial of this experiment has exactly the same parameter
        assignments, copy its observation log to this trial and finalize it
        Succeeded without running the workload. No reference counterpart —
        on TPU, a duplicate suggestion (small discrete spaces, categorical
        resampling) would otherwise re-burn a full training run.

        Scope, by design: only PREVIOUSLY COMPLETED trials match. Identical
        suggestions dispatched in the same reconcile batch (parallel > 1)
        all execute in full — deduping against in-flight twins would need a
        subscription on their completion and buys little, since duplicate
        suggestions mostly arrive across reconciles as a search converges.
        Checkpoint-lineage trials (persisted ``checkpoint-lineage`` label)
        are excluded as sources: their metrics reflect training inherited
        from a parent checkpoint, not a from-scratch run with these
        assignments."""
        key = tuple(sorted((a.name, a.value) for a in trial.parameter_assignments))
        if not key:
            return False  # nothing to match on; run the trial
        source = None
        for t in self.state.list_trials(exp.name):
            if (
                t.name != trial.name
                and t.condition == TrialCondition.SUCCEEDED
                and t.labels.get(self.LINEAGE_LABEL) != "1"
                and tuple(sorted((a.name, a.value) for a in t.parameter_assignments)) == key
            ):
                source = t
                break
        if source is None:
            return False
        logs = self.obs_store.get_observation_log(source.name)
        if logs:
            self.obs_store.report_observation_log(trial.name, logs)
        trial.observation = self.obs_store.folded(
            trial.name, exp.spec.objective.all_metric_names()
        )
        # pass through RUNNING so start_time is stamped — rung-cohort
        # algorithms (hyperband) sort trials by start_time, and a None
        # there would silently misplace the reused trial in its bracket
        trial.set_condition(
            TrialCondition.RUNNING, "TrialRunning",
            f"reusing result of trial {source.name}",
        )
        trial.set_condition(
            TrialCondition.SUCCEEDED,
            "DuplicateResultReused",
            f"reused result of trial {source.name} (identical assignments)",
        )
        self._record_terminal(exp, trial)
        self.events.put(TrialEvent(exp.name, trial.name, trial.condition))
        return True

    def kill(self, trial_name: str) -> None:
        """Early-stop / parallel-shrink kill (reference deleteTrials) — a
        deliberate decision, recorded so a later shutdown can't relabel the
        trial SchedulerShutdown and get it wrongly requeued on resume."""
        with self._lock:
            self._intentional_kills.add(trial_name)
            for i, (exp, t) in enumerate(self._waiting):
                if t.name == trial_name:
                    self._waiting.pop(i)
                    self._checkpoint_dirs.pop(trial_name, None)
                    self._clear_enqueue(trial_name, exp.name)
                    t.set_condition(TrialCondition.KILLED, "TrialKilled", "killed while pending")
                    self.state.update_trial(t)
                    self._trace_end_trial(exp.name, t)
                    self.events.put(TrialEvent(exp.name, t.name, t.condition))
                    return
        h = self._handles.get(trial_name)
        if h is not None:
            h.kill()
            return
        mf = self._mf()
        if mf is not None:
            # neither queued nor running: a rung-paused multi-fidelity trial
            # is killed in place and removed from its rung's candidates
            mf.kill_paused(trial_name, self)

    def kill_all(self) -> None:
        """Controller shutdown: kill everything, marking trials with the
        SchedulerShutdown reason so a cross-process resume
        (ExperimentController.load_experiment) can requeue them — shutdown is
        an artifact of the controller's lifetime, not a search decision."""
        self._shutdown.set()
        tr = self._tr()
        with self._lock:
            waiting = list(self._waiting)
            self._waiting.clear()
            self._enqueue_seq.clear()
            self._enqueued_at.clear()
            self._stall_emitted.clear()
            self._head_key, self._head_credits = None, 0
            self._gate_since.clear()
            self._gate_held.clear()
            queue_spans = dict(self._queue_spans)
            self._queue_spans.clear()
        for exp, t in waiting:
            t.set_condition(TrialCondition.KILLED, "SchedulerShutdown", "scheduler shutdown")
            self.state.update_trial(t)
            if tr is not None:
                tr.end_span(queue_spans.get(t.name), aborted="shutdown")
                self._trace_end_trial(exp.name, t)
        for h in list(self._handles.values()):
            h.kill()

    def active_count(self) -> int:
        with self._lock:
            return len(self._waiting) + len(self._handles)

    def is_active(self, trial_name: str) -> bool:
        with self._lock:
            return trial_name in self._handles or any(
                t.name == trial_name for _, t in self._waiting
            )

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.time() + timeout
        for t in list(self._threads):
            remaining = None if deadline is None else max(0.0, deadline - time.time())
            t.join(timeout=remaining)

    def quiesce(self, experiment_name: str, timeout: float = 10.0) -> bool:
        """Wait until no trial of this experiment is queued or holds a worker
        slot (and hence a gang allocation). A trial's terminal condition is
        persisted BEFORE its worker's finally-block releases the devices, so
        an observer that saw the experiment complete can be a few hundred
        microseconds ahead of the allocator; callers that are about to hand
        the chips to something else wait here instead of racing. Returns
        False on timeout (e.g. an abandoned zombie trial being reaped)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                # snapshot: _run_trial's finally pops _handles under its own
                # lock stints, and get_trial yields the GIL mid-generator
                handle_names = list(self._handles)
                waiting = [t.experiment_name for _, t in self._waiting]
            busy = any(
                self.state.get_trial(experiment_name, n) is not None
                for n in handle_names
            ) or experiment_name in waiting
            if not busy:
                return True
            time.sleep(0.005)
        return False

    # -- dispatch loop -------------------------------------------------------

    def _dispatch(self) -> None:
        """Start every waiting trial/pack whose gang allocation fits.

        Waiting trials are first grouped into dispatch units by
        packing.plan_packs: packable same-template trials of one experiment
        merge into packs of up to K = pack_capacity(exp) members sharing ONE
        gang allocation and one compiled program; everything else dispatches
        solo through the unchanged per-trial path.

        Units are then walked in fair-share policy order (priority + aging,
        deficit-weighted fair share, arrival order — controller/fairshare.py)
        with quota enforcement, backfill-vs-reservation, and preemption
        planning. When no experiment in the system sets any fair-share knob,
        the walk degenerates to the legacy path: arrival order, every unit
        tries its allocation, misses requeue — FIFO preserved exactly."""
        from . import fairshare as fs
        from .packing import plan_packs

        now = time.time()
        with self._lock:
            if self._dispatch_paused:
                # a batch submission holds the dispatch barrier: this pass
                # would see a partial batch; the barrier exit re-runs it
                return
            self._threads = [t for t in self._threads if t.is_alive()]
            cs = self._cs()
            warm = None
            if cs is not None:
                # pack formation prefers units whose dispatch group already
                # has a warm executable (registry dict hit; advisory)
                def warm(exp, trial, _cs=cs):
                    try:
                        return _cs.is_warm(exp.spec, trial)
                    except Exception:
                        return False
            units = plan_packs(self._waiting, warm=warm)
            self._waiting = []
            entries: List[fs.QueueEntry] = []
            for exp, members in units:
                requested = max(exp.spec.trial_template.resources.num_devices, 1)
                entries.append(
                    fs.QueueEntry(
                        exp=exp,
                        trials=members,
                        needed=min(requested, self.allocator.total),
                        requested=requested,
                        seq=min(self._enqueue_seq.get(t.name, 0) for t in members),
                        enqueued_at=min(
                            self._enqueued_at.get(t.name, now) for t in members
                        ),
                        priority=fs.priority_of(exp),
                    )
                )
            fairshare_on = any(fs.uses_fairshare(e.exp) for e in entries) or any(
                u.fairshare for u in self._running.values()
            )
            ordered = (
                self._policy.order(entries, now)
                if fairshare_on
                else self._fingerprint_grouped(entries)
            )
            free = self.allocator.free_count
            leftover: List[fs.QueueEntry] = []
            head_seen = False
            if not fairshare_on:
                self._head_key, self._head_credits = None, 0
            for e in ordered:
                n = e.needed
                quota = fs.device_quota_of(e.exp)
                if quota is not None and self._usage.get(e.exp.name, 0) + n > quota:
                    # quota-blocked: holds no reservation — units behind it
                    # flow around freely
                    leftover.append(e)
                    continue
                if not fairshare_on and self._gate_hold(e, now):
                    # compile-gated: the unit's executable is still
                    # compiling in the service — hold it (units behind flow
                    # around, like a quota block) up to compile_gate_seconds,
                    # then fall back to inline compilation
                    leftover.append(e)
                    continue
                if fairshare_on:
                    if not head_seen and free < n:
                        # first blocked unit in policy order becomes the
                        # reserving head: chips released while it stays
                        # blocked accrue to its credits and cannot be
                        # backfilled, so its gang assembles monotonically
                        head_seen = True
                        if self._head_key != e.key:
                            self._head_key, self._head_credits = e.key, 0
                        self._head_credits = min(self._head_credits, n)
                        self._plan_preemption(e, free)
                        leftover.append(e)
                        continue
                    reserved = min(self._head_credits, free) if head_seen else 0
                    if free - reserved < n:
                        leftover.append(e)
                        continue
                devices = self.allocator.acquire(
                    n, holder=e.key, experiment=e.exp.name
                )
                if devices is None:
                    leftover.append(e)
                    continue
                free -= n
                if e.key == self._head_key:
                    self._head_key, self._head_credits = None, 0
                self._start_unit(e, devices)
            if fairshare_on and not head_seen:
                # the previous head dispatched or left the queue
                self._head_key, self._head_credits = None, 0
            self._waiting = [(e.exp, t) for e in leftover for t in e.trials]
            self._note_queue_state(leftover, now)

    def _fingerprint_grouped(self, entries):
        """Legacy-path dispatch ordering (ISSUE 7 + ISSUE 8): units whose
        trials compile to the same program (equal semantic dispatch-group
        key, analysis/program.py) dispatch consecutively, so the first
        unit's trace/compile warms the jit and persistent-XLA caches for
        the rest; with the AOT compile service attached, groups whose
        executable is already WARM in the registry dispatch before cold
        groups (one dict lookup per group). Stable: groups appear at their
        first member's arrival position, members keep arrival order, and
        units with no key (analysis off, command template, no probe) are
        singleton groups — with no keys (or no compile service) the walk
        is the identity, preserving FIFO exactly. Caller holds the
        scheduler lock."""
        from ..analysis import program as semantic

        cs = self._cs()
        first_pos: Dict[Any, int] = {}
        rank: Dict[Any, int] = {}
        keyed = []
        for i, e in enumerate(entries):
            try:
                key = semantic.dispatch_group_key(e.exp.spec, e.trials[0])
            except Exception:
                key = None  # advisory: ordering must never break dispatch
            gid = ("solo", i) if key is None else ("fp", key)
            if gid not in first_pos:
                first_pos[gid] = i
                warm = False
                if cs is not None and key is not None:
                    from ..compilesvc.service import STATE_WARM

                    warm = cs.state_for_key(key) == STATE_WARM
                rank[gid] = 0 if warm else 1
            keyed.append((rank[gid], first_pos[gid], i, e))
        keyed.sort(key=lambda t: (t[0], t[1], t[2]))
        return [e for _, _, _, e in keyed]

    def _gate_hold(self, entry, now: float) -> bool:
        """Compile-gated dispatch: True to hold a ready unit because the
        service is still compiling its program (state pending/compiling)
        and the hold is younger than compile_gate_seconds. The consult is a
        dict lookup — dispatch never blocks inline on XLA; when the gate
        expires (or the compile fails) the unit dispatches and compiles
        inline exactly as before. Caller holds the scheduler lock."""
        cs = self._cs()
        if cs is None or self.compile_gate_seconds <= 0:
            return False
        from ..analysis import program as semantic
        from ..compilesvc.service import STATE_COMPILING, STATE_PENDING

        try:
            key = semantic.dispatch_group_key(entry.exp.spec, entry.trials[0])
        except Exception:
            key = None
        if key is None:
            return False
        state = cs.state_for_key(key)
        if state not in (STATE_PENDING, STATE_COMPILING):
            self._gate_since.pop(key, None)  # warm/failed/unknown: no hold
            return False
        since = self._gate_since.setdefault(key, now)
        remaining = self.compile_gate_seconds - (now - since)
        if remaining <= 0:
            return False  # expired: inline-compile fallback (never re-held
            # for this group until its state leaves pending/compiling)
        for t in entry.trials:
            # span bookkeeping: the queue_wait span of a gated trial gets
            # compileGated/compileGateSeconds stamped at dispatch
            self._gate_held.setdefault(t.name, since)
        if not self._gate_timer_live:
            # one wake timer per hold window so an expired gate re-runs the
            # dispatch pass even if no compile transition fires
            self._gate_timer_live = True
            timer = threading.Timer(min(remaining, 1.0) + 0.02, self._gate_wake)
            timer.daemon = True
            timer.start()
        return True

    def _gate_wake(self) -> None:
        with self._lock:
            self._gate_timer_live = False
        if not self._shutdown.is_set():
            self._dispatch()

    def _start_unit(self, entry, devices) -> None:
        """Spawn the worker thread for one dispatch unit (solo or pack) and
        register its running-unit record; caller holds the scheduler lock."""
        from .fairshare import RunningUnit, priority_of, uses_fairshare

        exp, members = entry.exp, entry.trials
        n = len(devices)
        if self.journal is not None:
            # one intent per dispatch unit: replay (and `katib-tpu recover`)
            # can see which trials shared a gang when the crash hit
            self.journal.append(
                "dispatch", exp.name,
                trials=[t.name for t in members], devices=n,
            )
        if n < entry.requested:
            for t in members:
                self._devices_clamped(exp, t, entry.requested, n)
        for t in members:
            self._clear_enqueue(t.name, exp.name)
        self._usage[exp.name] = self._usage.get(exp.name, 0) + n
        template = exp.spec.trial_template
        if len(members) == 1:
            trial = members[0]
            handle = TrialExecution()
            handles = [handle]
            self._handles[trial.name] = handle
            th = threading.Thread(
                target=self._run_trial,
                args=(exp, trial, devices, handle),
                name=f"trial-{trial.name}",
                daemon=True,
            )
        else:
            handles = [TrialExecution() for _ in members]
            for t, h in zip(members, handles):
                self._handles[t.name] = h
            self._record_pack_formed(exp, members)
            th = threading.Thread(
                target=self._run_pack,
                args=(exp, members, devices, handles),
                name=f"trial-pack-{members[0].name}",
                daemon=True,
            )
        self._unit_devices[entry.key] = list(devices)
        self._running[entry.key] = RunningUnit(
            key=entry.key,
            experiment=exp.name,
            trial_names=[t.name for t in members],
            n_devices=n,
            priority=priority_of(exp),
            # preemption is cooperative through ctx.report(): only
            # in-process single-host units can checkpoint-and-yield
            preemptible=template.command is None and template.resources.num_hosts <= 1,
            started=time.time(),
            fairshare=uses_fairshare(exp),
            handles=handles,
        )
        self._threads.append(th)
        th.start()

    def _plan_preemption(self, entry, free: int) -> None:
        """Ask the policy for a victim set that unblocks ``entry`` and
        signal it: lowest priority first, most-recent checkpoint first.
        Victims checkpoint-and-exit cooperatively at their next report; a
        victim that ignores the signal past the grace window is killed (it
        still requeues, resuming from its last checkpoint, if any). Caller
        holds the scheduler lock."""
        victims = self._policy.select_victims(
            entry.needed,
            free,
            entry.priority,
            list(self._running.values()),
            lambda t: self._last_checkpoint.get(t, 0.0),
        )
        if not victims:
            return
        # preemption is actively clearing chips for this gang — earmark the
        # currently-free chips too, so backfill can't take what the victims
        # are about to deliver
        self._head_credits = max(self._head_credits, min(entry.needed, free))
        for u in victims:
            u.preempt_signaled = True
            self._preempting.update(u.trial_names)
            for h in u.handles:
                h.preempt()
            log.info(
                "preempting %s (%d device(s), priority %d) for %s "
                "(%d device(s), priority %d)",
                ",".join(u.trial_names), u.n_devices, u.priority,
                entry.key, entry.needed, entry.priority,
            )
            if self.preemption_grace_seconds:
                timer = threading.Timer(
                    self.preemption_grace_seconds,
                    lambda hs=list(u.handles): [h.kill() for h in hs],
                )
                timer.daemon = True
                timer.start()

    def _note_queue_state(self, leftover, now: float) -> None:
        """Per-dispatch-pass queue observability: TrialQueueStalled warnings
        for trials pending past the threshold, plus the katib_queue_depth /
        katib_queue_wait_seconds / katib_fairshare_deficit gauges. Caller
        holds the scheduler lock."""
        depth: Dict[str, int] = {}
        oldest: Dict[str, float] = {}
        for e in leftover:
            for t in e.trials:
                depth[e.exp.name] = depth.get(e.exp.name, 0) + 1
                wait = max(now - self._enqueued_at.get(t.name, now), 0.0)
                oldest[e.exp.name] = max(oldest.get(e.exp.name, 0.0), wait)
                if (
                    self.queue_stall_seconds
                    and wait > self.queue_stall_seconds
                    and t.name not in self._stall_emitted
                ):
                    self._stall_emitted.add(t.name)
                    log.warning(
                        "trial %s has been pending %.0fs for %d device(s) "
                        "(free: %d) — head-of-line blocking, quota, or "
                        "starvation", t.name, wait, e.needed,
                        self.allocator.free_count,
                    )
                    if self.recorder is not None:
                        self.recorder.event(
                            e.exp.name, "Trial", t.name, "TrialQueueStalled",
                            f"pending for {wait:.0f}s waiting for {e.needed} "
                            f"device(s) (free: {self.allocator.free_count}); "
                            "see /api/queue for queue state",
                            warning=True,
                        )
        if self.metrics_registry is not None:
            names = set(depth) | self._gauged_experiments
            deficits = self._policy.deficits(sorted({e.exp.name for e in leftover}))
            for name in names:
                self.metrics_registry.set_gauge(
                    "katib_queue_depth", float(depth.get(name, 0)), experiment=name
                )
                self.metrics_registry.set_gauge(
                    "katib_queue_wait_seconds",
                    round(oldest.get(name, 0.0), 3),
                    experiment=name,
                )
                self.metrics_registry.set_gauge(
                    "katib_fairshare_deficit",
                    round(deficits.get(name, 0.0), 3),
                    experiment=name,
                )
            self._gauged_experiments = set(depth)

    def _devices_clamped(
        self, exp: Experiment, trial: Trial, requested: int, granted: int
    ) -> None:
        """An allocation the machine cannot satisfy is clamped rather than
        wedged forever — but silently shrinking a gang hides undersized
        hardware from the operator, so make it visible."""
        log.warning(
            "trial %s requested %d devices but the machine has %d; "
            "allocation clamped", trial.name, requested, granted,
        )
        if self.recorder is not None:
            self.recorder.event(
                exp.name, "Trial", trial.name, "TrialDevicesClamped",
                f"requested {requested} devices, machine total is {granted}; "
                "allocation clamped to the machine",
                warning=True,
            )

    def _record_pack_formed(self, exp: Experiment, members: Sequence[Trial]) -> None:
        from .packing import pack_capacity

        k = max(pack_capacity(exp), 1)
        tr = self._tr()
        if tr is not None:
            # instantaneous stage marker in each member's trace: the moment
            # pack formation merged it into a shared dispatch unit
            now = time.time()
            for t in members:
                mroot = tr.trial_root(exp.name, t.name)
                if mroot is not None:
                    tr.record_span(
                        "pack_formation", exp.name, mroot.trace_id,
                        mroot.span_id, start=now, end=now,
                        members=len(members), capacity=k,
                    )
        if self.metrics_registry is not None:
            self.metrics_registry.inc("katib_pack_formed_total", experiment=exp.name)
            self.metrics_registry.inc(
                "katib_trial_packed_total", value=float(len(members)),
                experiment=exp.name,
            )
            self.metrics_registry.set_gauge(
                "katib_pack_occupancy", len(members) / k, experiment=exp.name
            )
        if self.recorder is not None:
            self.recorder.event(
                exp.name, "Trial", members[0].name, "PackFormed",
                f"packed {len(members)}/{k} trials into one program: "
                + ", ".join(t.name for t in members),
            )

    def _run_trial(self, exp: Experiment, trial: Trial, devices, handle: TrialExecution) -> None:
        from ..tracing import pop_log_context, push_log_context

        restarted = False
        requeued = False
        started = time.time()
        timer = None
        ctx: Optional[TrialContext] = None
        abandoned: Optional[threading.Thread] = None
        timed_out = threading.Event()
        tr = self._tr()
        tm = self._tm()
        root = tr.trial_root(exp.name, trial.name) if tr is not None else None
        run_span = exec_span = None
        if root is not None:
            run_span = tr.start_span(
                "run", exp.name, root.trace_id, root.span_id,
                attrs={"devices": len(devices)},
            )
        if tm is not None:
            # resource sampling for this run stint (telemetry.py): starts as
            # in-process attribution; the executor re-points it at the child
            # pids via ctx.on_subprocess when the trial forks
            tm.register_trial(exp.name, trial.name)
        log_token = push_log_context(
            experiment=exp.name, trial=trial.name,
            trace_id=root.trace_id if root is not None else "",
        )
        try:
            trial.set_condition(TrialCondition.RUNNING, "TrialRunning", "Trial is running")
            self.state.update_trial(trial)

            if self.trial_timeout:
                def _deadline():
                    timed_out.set()
                    handle.kill()

                timer = threading.Timer(self.trial_timeout, _deadline)
                timer.daemon = True
                timer.start()

            setup_span = None
            if run_span is not None:
                setup_span = tr.start_span(
                    "executor_setup", exp.name, run_span.trace_id, run_span.span_id
                )
            ctx = self._build_context(exp, trial, devices, handle)
            spec = exp.spec
            if (
                spec.trial_template.resources.num_hosts > 1
                and spec.trial_template.function is None
            ):
                # gang of worker processes forming one jax.distributed system
                executor = self._multihost
            elif spec.trial_template.command is not None:
                executor = self._subprocess
            else:
                executor = self._in_process
            if run_span is not None:
                tr.end_span(setup_span, executor=type(executor).__name__)
                exec_span = tr.start_span(
                    "execute", exp.name, run_span.trace_id, run_span.span_id,
                    attrs={"executor": type(executor).__name__},
                )
                # runtime-side spans (compile boundary, steps, checkpoint,
                # flush barriers) hang off the execute span
                ctx.bind_trace(tr, exp.name, run_span.trace_id, exec_span.span_id)
            result, abandoned = self._execute_bounded(
                executor, exp, trial, ctx, handle, timed_out
            )
            if exec_span is not None:
                tr.end_span(exec_span, outcome=result.outcome.value)

            if timed_out.is_set() and result.outcome == TrialOutcome.KILLED:
                # deadline exceeded counts against maxFailedTrialCount
                result = ExecutionResult(
                    TrialOutcome.FAILED,
                    f"trial exceeded timeout of {self.trial_timeout}s",
                )
            result = self._convert_backend_loss(trial, result, devices)
            # Preemption first: a preempted trial is neither classified nor
            # finalized — it requeues as resumable and its next run's fold
            # continues the same observation log (checkpoint resume) or a
            # clean one (no checkpoint).
            if self._preempt_applies(trial, result):
                preempt_start = time.time()
                requeued = self._requeue_preempted(exp, trial)
                if requeued and run_span is not None:
                    tr.record_span(
                        "preempted", exp.name, run_span.trace_id, run_span.span_id,
                        start=preempt_start, end=time.time(),
                        resumable=trial.name in self._last_checkpoint,
                    )
            if not requeued:
                # Classify (observation fold + success/failure conditions)
                # BEFORE the restart decision: a non-zero-exit trial a
                # success_condition rescues must not burn max_trial_restarts
                # attempts, and an rc=0 trial a failure_condition flips to
                # Failed must be retried like any other failure.
                fin_span = None
                if run_span is not None:
                    fin_span = tr.start_span(
                        "finalize", exp.name, run_span.trace_id, run_span.span_id
                    )
                result, observation = self._classify(exp, trial, result)
                paused = False
                mf = self._mf()
                if mf is not None and result.outcome == TrialOutcome.COMPLETED:
                    # rung-boundary consult (controller/multifidelity.py): a
                    # multi-fidelity trial that completed its assigned budget
                    # is PAUSED — checkpoint + observations intact — instead
                    # of finalized; a promotion resubmits it at the next
                    # fidelity. Non-asha experiments return False untouched.
                    try:
                        paused = mf.on_rung_boundary(exp, trial, observation, self)
                    except Exception:
                        log.warning("rung boundary consult failed", exc_info=True)
                if not paused:
                    restarted = self._maybe_restart(exp, trial, result)
                    if not restarted:
                        self._finalize(exp, trial, result, observation)
                if fin_span is not None:
                    tr.end_span(fin_span, restarted=restarted, rung_paused=paused)
        except Exception:
            trial.set_condition(TrialCondition.FAILED, "TrialFailed", traceback.format_exc(limit=5))
            self.state.update_trial(trial)
        finally:
            if timer is not None:
                timer.cancel()
            if tm is not None:
                # the stint's resource summary lands on the trial root span
                # BEFORE it is ended/persisted below
                self._telemetry_finalize(tm, trial.name, root)
            if (
                self.step_stats is not None
                and ctx is not None
                and ctx.step_clock is not None
            ):
                # stint rows + RetraceStorm/StepTimeRegression + rollups.
                # Requeued/restarted stints skip persistence: their rows
                # would be truncated to the last checkpoint on resume (or
                # the log dropped on restart) — the next stint re-measures.
                self.step_stats.finalize_stint(
                    exp, trial.name, ctx.step_clock, self.obs_store,
                    n_devices=len(devices),
                    write_rows=not (requeued or restarted),
                )
            if run_span is not None:
                tr.end_span(exec_span)  # no-op unless an exception skipped it
                tr.end_span(run_span, requeued=requeued, restarted=restarted)
            if tr is not None and not requeued and not restarted:
                self._trace_end_trial(exp.name, trial)
            pop_log_context(log_token)
            with self._lock:
                self._running.pop(trial.name, None)
                self._unit_devices.pop(trial.name, None)
                if not requeued:
                    self._preempting.discard(trial.name)
            if abandoned is not None and abandoned.is_alive():
                # An abandoned in-process trial may still be running JAX work
                # on these chips — quarantine them (don't hand them to the
                # next trial) until the zombie thread actually exits.
                self._quarantine(trial.name, devices, abandoned, exp, started)
            else:
                self._release_allocation(exp, devices, started)
            with self._lock:
                self._handles.pop(trial.name, None)
                if not restarted and not requeued:
                    self._checkpoint_dirs.pop(trial.name, None)
                    self._restarts.pop(trial.name, None)
                    self._last_checkpoint.pop(trial.name, None)
            self.events.put(TrialEvent(exp.name, trial.name, trial.condition))
            self._dispatch()

    def _report_heartbeat_hook(
        self, names: Sequence[str], holder: str
    ) -> Optional[Callable[[], None]]:
        """Combined per-report liveness hook: telemetry watchdog heartbeats
        for every member plus the device plane's lease heartbeat for the
        unit (which is also where scheduled chaos faults fire). None when
        both subsystems are off, so ctx.report pays one check."""
        tm, dp = self._tm(), self._dp()
        if tm is None and dp is None:
            return None

        def hook(_tm=tm, _dp=dp, _names=tuple(names), _holder=holder):
            if _tm is not None:
                for n in _names:
                    _tm.heartbeat(n)
            if _dp is not None:
                _dp.heartbeat(_holder)

        return hook

    def _telemetry_finalize(self, tm, trial_name: str, root) -> None:
        """Close one trial's telemetry stint: unregister (persists its
        sample ring) and stamp the peak-RSS / peak-HBM / mean-CPU summary
        onto the trial's root span so the trace answers cost, not just
        time. ``root`` is None when tracing is off."""
        summary = tm.unregister_trial(trial_name)
        if summary and root is not None:
            root.set(
                peak_rss_bytes=summary["peakRssBytes"],
                peak_hbm_bytes=summary["peakHbmBytes"],
                mean_cpu_percent=summary["meanCpuPercent"],
            )

    def _run_pack(
        self,
        exp: Experiment,
        trials: List[Trial],
        devices,
        handles: List[TrialExecution],
    ) -> None:
        """Run one formed pack to completion: K trials, one gang allocation,
        one PackedTrialExecutor call, then per-trial condition fan-out —
        each member is classified/finalized independently, exactly like K
        solo trials would be."""
        from ..tracing import pop_log_context, push_log_context
        from .packing import PACK_LABEL

        timer = None
        started = time.time()
        requeued: set = set()
        ctx = None
        abandoned: Optional[threading.Thread] = None
        timed_out = threading.Event()
        pack_id = f"{trials[0].name}x{len(trials)}"
        tr = self._tr()
        tm = self._tm()
        if tm is not None:
            for t in trials:
                tm.register_trial(exp.name, t.name)  # in-process: shared attribution
        # one gang-level trace per pack (root `pack` span + K member child
        # spans); each member's own trial trace gets a `run` span linking to
        # it, so both the per-trial and the shared-program views connect
        gang = (
            tr.begin_gang(exp.name, pack_id, [t.name for t in trials])
            if tr is not None
            else None
        )
        member_runs: Dict[str, Any] = {}
        if gang is not None:
            for t in trials:
                mroot = tr.trial_root(exp.name, t.name)
                if mroot is not None:
                    member_runs[t.name] = tr.start_span(
                        "run", exp.name, mroot.trace_id, mroot.span_id,
                        attrs={"pack": pack_id, "packTraceId": gang.trace_id},
                    )
        log_token = push_log_context(
            experiment=exp.name, trial=pack_id,
            trace_id=gang.trace_id if gang is not None else "",
        )
        try:
            for t in trials:
                t.labels[PACK_LABEL] = pack_id
                t.set_condition(
                    TrialCondition.RUNNING, "TrialRunning",
                    f"Trial is running (packed, {len(trials)} members)",
                )
                self.state.update_trial(t)

            if self.trial_timeout:
                def _deadline():
                    timed_out.set()
                    for h in handles:
                        h.kill()

                timer = threading.Timer(self.trial_timeout, _deadline)
                timer.daemon = True
                timer.start()

            ctx = self._build_pack_context(exp, trials, devices, handles)
            # one demuxed report() heartbeats every member — the watchdog
            # sees the pack's shared step loop, not K separate clocks — and
            # ticks the gang's device lease in the plane
            hook = self._report_heartbeat_hook(
                [t.name for t in trials], trials[0].name
            )
            if hook is not None:
                ctx.on_report = hook
            if gang is not None:
                # shared compiled program: compile/steps/flush spans land in
                # the gang trace under the pack root
                ctx.bind_trace(tr, exp.name, gang.trace_id, gang.root.span_id)
            executor = self._pack_executor(exp, trials)
            results, abandoned = self._execute_pack_bounded(
                executor, exp, trials, ctx, handles, timed_out
            )
            results = self._convert_pack_backend_loss(
                pack_id, trials, results, devices
            )
            for trial, result in zip(trials, results):
                if timed_out.is_set() and result.outcome == TrialOutcome.KILLED:
                    result = ExecutionResult(
                        TrialOutcome.FAILED,
                        f"trial exceeded timeout of {self.trial_timeout}s",
                    )
                # a pack preempts as one unit, but members requeue
                # individually — they re-pack (or run solo) on redispatch
                if self._preempt_applies(trial, result):
                    if self._requeue_preempted(exp, trial):
                        requeued.add(trial.name)
                        if gang is not None:
                            tr.end_span(
                                gang.members.get(trial.name), outcome="preempted"
                            )
                            tr.end_span(
                                member_runs.get(trial.name), requeued=True
                            )
                        continue
                result, observation = self._classify(exp, trial, result)
                mf = self._mf()
                if mf is not None and result.outcome == TrialOutcome.COMPLETED:
                    # packed bottom rungs hit the same boundary consult as
                    # solo trials: each member pauses (or promotes)
                    # independently when the shared program completes
                    try:
                        rung_paused = mf.on_rung_boundary(
                            exp, trial, observation, self
                        )
                    except Exception:
                        rung_paused = False
                        log.warning("rung boundary consult failed", exc_info=True)
                    if rung_paused:
                        with self._lock:
                            self._checkpoint_dirs.pop(trial.name, None)
                            self._restarts.pop(trial.name, None)
                            self._last_checkpoint.pop(trial.name, None)
                        if gang is not None:
                            tr.end_span(
                                gang.members.get(trial.name), outcome="rung-paused"
                            )
                            tr.end_span(
                                member_runs.get(trial.name), rung_paused=True
                            )
                        continue
                restarted = self._maybe_restart(exp, trial, result)
                if not restarted:
                    self._finalize(exp, trial, result, observation)
                    with self._lock:
                        self._checkpoint_dirs.pop(trial.name, None)
                        self._restarts.pop(trial.name, None)
                        self._last_checkpoint.pop(trial.name, None)
                if gang is not None:
                    tr.end_span(
                        gang.members.get(trial.name), outcome=result.outcome.value
                    )
                    tr.end_span(member_runs.get(trial.name), restarted=restarted)
        except Exception:
            tb = traceback.format_exc(limit=5)
            for t in trials:
                if not t.is_terminal:
                    t.set_condition(TrialCondition.FAILED, "TrialFailed", tb)
                    self.state.update_trial(t)
        finally:
            if timer is not None:
                timer.cancel()
            if tm is not None:
                for t in trials:
                    self._telemetry_finalize(
                        tm, t.name,
                        tr.trial_root(exp.name, t.name) if tr is not None else None,
                    )
            if (
                self.step_stats is not None
                and ctx is not None
                and getattr(ctx, "_step_clocks", None) is not None
            ):
                # per-member stint rows + detectors, then the gang-level
                # straggler check; requeued members skip persistence (their
                # rows truncate to the last checkpoint on resume)
                self.step_stats.finalize_pack(
                    exp, [t.name for t in trials], ctx._step_clocks,
                    self.obs_store, n_devices=len(devices),
                    requeued=[t.name in requeued for t in trials],
                )
            if gang is not None:
                for t in trials:
                    tr.end_span(gang.members.get(t.name))
                    tr.end_span(member_runs.get(t.name))
                tr.end_span(gang.root)
                for t in trials:
                    if t.name not in requeued:
                        self._trace_end_trial(exp.name, t)
            pop_log_context(log_token)
            with self._lock:
                self._running.pop(trials[0].name, None)
                self._unit_devices.pop(trials[0].name, None)
                for t in trials:
                    if t.name not in requeued:
                        self._preempting.discard(t.name)
            if abandoned is not None and abandoned.is_alive():
                self._quarantine(pack_id, devices, abandoned, exp, started)
            else:
                self._release_allocation(exp, devices, started)
            with self._lock:
                for t in trials:
                    self._handles.pop(t.name, None)
            for t in trials:
                self.events.put(TrialEvent(exp.name, t.name, t.condition))
            self._dispatch()

    def _pack_executor(self, exp: Experiment, trials: List[Trial]):
        """Executor for one formed pack: an opted-in fused population sweep
        (every member carries the fused label and the template exposes a
        population_program probe) runs through the FusedPopulationExecutor
        — the whole sweep in compiled lax.scan chunks; anything else keeps
        the PackedTrialExecutor path unchanged."""
        from ..runtime import population as pop
        from .packing import FusedPopulationExecutor, PackedTrialExecutor

        if (
            self.fused_population
            and all(pop.FUSED_LABEL in t.labels for t in trials)
            and pop.fused_applicable(exp.spec) is None
        ):
            return FusedPopulationExecutor(
                self.obs_store,
                chunk_generations=self.population_chunk_generations,
                stream=self.population_stream,
                compile_service=self._cs(),
                metrics=self.metrics_registry,
            )
        return PackedTrialExecutor(self.obs_store)

    def _execute_pack_bounded(
        self,
        executor,
        exp: Experiment,
        trials: List[Trial],
        ctx,
        handles: List[TrialExecution],
        timed_out: threading.Event,
    ) -> "tuple[List[ExecutionResult], Optional[threading.Thread]]":
        """Pack counterpart of _execute_bounded. Individual member kills are
        cooperative (frozen at the next ctx.report); the grace/abandon
        machinery engages only when EVERY member was asked to stop (timeout
        or shutdown) and the shared program still refuses to exit — there is
        one program, so there is one thread to abandon."""
        from ..tracing import push_log_context

        box: Dict[str, Any] = {}

        def _exec():
            push_log_context(
                experiment=exp.name, trial=f"{trials[0].name}x{len(trials)}"
            )
            try:
                box["results"] = executor.execute(exp, trials, ctx, handles)
            except BaseException:
                box["error"] = traceback.format_exc(limit=5)

        worker = threading.Thread(
            target=_exec, name=f"pack-exec-{trials[0].name}", daemon=True
        )
        worker.start()
        abandon_at = None
        while worker.is_alive():
            worker.join(timeout=0.2)
            if abandon_at is None and all(h.kill_requested for h in handles):
                abandon_at = time.time() + self.KILL_GRACE_SECONDS
            if abandon_at is not None and time.time() > abandon_at and worker.is_alive():
                if timed_out.is_set():
                    outcome, reason = (
                        TrialOutcome.FAILED,
                        f"trial exceeded timeout of {self.trial_timeout}s",
                    )
                else:
                    outcome, reason = TrialOutcome.KILLED, "kill requested"
                msg = (
                    f"{reason}; pack did not stop within "
                    f"{self.KILL_GRACE_SECONDS}s grace, abandoned"
                )
                return [ExecutionResult(outcome, msg) for _ in trials], worker
        if "error" in box:
            return (
                [ExecutionResult(TrialOutcome.FAILED, box["error"]) for _ in trials],
                None,
            )
        return box["results"], None

    def _build_pack_context(
        self,
        exp: Experiment,
        trials: List[Trial],
        devices,
        handles: List[TrialExecution],
    ):
        """Batched analogue of _build_context: per-member reporters (with
        raise_on_stop=False — stopping is masking, not unwinding, and the
        kill check belongs to the packed context so one member's kill can't
        unwind the shared program), stacked assignments, and per-member
        workdir/checkpoint-dir lists."""
        from ..runtime.packed import PackedTrialContext
        from .packing import stack_assignments

        spec = exp.spec
        reporters = []
        for t in trials:
            monitor = None
            if t.early_stopping_rules:
                monitor = EarlyStoppingMonitor(
                    t.early_stopping_rules,
                    spec.objective.objective_metric_name,
                    spec.objective.type,
                )
            reporters.append(
                MetricsReporter(
                    store=self.obs_store,
                    trial_name=t.name,
                    monitor=monitor,
                    raise_on_stop=False,
                )
            )
        workdirs: List[Optional[str]] = []
        for t in trials:
            workdir = None
            if self.workdir_root:
                import os

                workdir = os.path.join(self.workdir_root, exp.name, t.name)
                os.makedirs(workdir, exist_ok=True)
            workdirs.append(workdir)
        ctx = PackedTrialContext(
            trial_names=[t.name for t in trials],
            experiment_name=exp.name,
            assignments=stack_assignments(trials),
            reporters=reporters,
            kill_events=[h.kill_event for h in handles],
            workdirs=workdirs,
            checkpoint_dirs=[self._checkpoint_dirs.get(t.name) for t in trials],
            member_labels=[dict(t.labels) for t in trials],
            devices=list(devices),
            topology=spec.trial_template.resources.topology,
            preempt_events=[h.preempt_event for h in handles],
            # a fused chunk checkpoint covers EVERY member: stamp them all,
            # so preempted members requeue as resumable (logs kept)
            on_checkpoint=lambda step, _names=[t.name for t in trials]: [
                self._note_checkpoint(n) for n in _names
            ],
        )
        if self.step_stats is not None:
            # one clock per member: the demux marks each active member's
            # clock per report; fused sweeps time chunks instead
            # (note_step_seconds) and the member index keys the straggler
            # injection seam
            ctx._step_clocks = [
                self.step_stats.clock_for(member_index=i)
                for i in range(len(trials))
            ]
        return ctx

    KILL_GRACE_SECONDS = 30.0

    def _execute_bounded(
        self, executor, exp: Experiment, trial: Trial, ctx, handle: TrialExecution,
        timed_out: threading.Event,
    ) -> "tuple[ExecutionResult, Optional[threading.Thread]]":
        """Run the executor on a worker thread so a kill/timeout cannot leak
        the gang allocation. Subprocess trials die on SIGTERM; in-process
        trials unwind cooperatively (TrialKilled raised at their next
        ctx.report()). A function that never reports and never returns is
        abandoned after a grace period — its daemon thread keeps running (a
        Python thread can't be force-killed) and is returned to the caller so
        the devices it may still be using get quarantined, not reissued."""
        from ..tracing import push_log_context

        box: Dict[str, Any] = {}

        def _exec():
            push_log_context(experiment=exp.name, trial=trial.name)
            try:
                box["result"] = executor.execute(exp, trial, ctx, handle)
            except BaseException:
                box["error"] = traceback.format_exc(limit=5)

        worker = threading.Thread(
            target=_exec, name=f"trial-exec-{trial.name}", daemon=True
        )
        worker.start()
        abandon_at = None
        while worker.is_alive():
            worker.join(timeout=0.2)
            if handle.kill_requested and abandon_at is None:
                abandon_at = time.time() + self.KILL_GRACE_SECONDS
            if abandon_at is not None and time.time() > abandon_at and worker.is_alive():
                reason = (
                    f"trial exceeded timeout of {self.trial_timeout}s"
                    if timed_out.is_set()
                    else "kill requested"
                )
                return ExecutionResult(
                    TrialOutcome.FAILED if timed_out.is_set() else TrialOutcome.KILLED,
                    f"{reason}; trial did not stop within "
                    f"{self.KILL_GRACE_SECONDS}s grace, abandoned",
                ), worker
        if "error" in box:
            return ExecutionResult(TrialOutcome.FAILED, box["error"]), None
        return box["result"], None

    def _quarantine(
        self,
        trial_name: str,
        devices: Sequence[Any],
        worker: threading.Thread,
        exp: Experiment,
        started: float,
    ) -> None:
        """Hold the gang allocation of an abandoned (zombie) trial until its
        worker thread actually exits, then release and re-dispatch. The
        zombie keeps burning the chips, so the experiment stays charged (and
        quota-attributed) until the actual release.

        With the device plane attached the hold is a ZOMBIE LEASE, not a
        bare counter: past runtime.device_lease_seconds the plane reclaims
        the chips into the pool (DeviceLeaseRevoked) even if the zombie
        thread never exits — the pre-plane ``_quarantined`` counter counted
        these devices forever without ever returning them (the ISSUE 12
        leak). The late-exiting zombie's release is then a no-op."""
        dp = self._dp()
        if dp is not None:
            dp.mark_zombie(devices, holder=trial_name)
        with self._lock:
            self._quarantined += len(devices)
        log.warning(
            "quarantining %d device(s) of abandoned trial %s until its "
            "worker thread exits", len(devices), trial_name,
        )

        def _reap():
            worker.join()
            with self._lock:
                self._quarantined -= len(devices)
            log.warning(
                "abandoned trial %s finally exited; releasing %d quarantined "
                "device(s)", trial_name, len(devices),
            )
            self._release_allocation(exp, devices, started)
            self._dispatch()

        threading.Thread(
            target=_reap, daemon=True, name=f"reap-{trial_name}"
        ).start()

    def _release_allocation(self, exp: Experiment, devices: Sequence[Any], started: float) -> None:
        """The one release path for gang allocations: fair-share usage is
        charged (device-seconds / weight), the experiment's quota attribution
        drops, and chips released while a blocked head holds the reservation
        accrue to its backfill-proof credits."""
        from .fairshare import weight_of

        elapsed = max(time.time() - started, 0.0)
        with self._lock:
            self._usage[exp.name] = max(0, self._usage.get(exp.name, 0) - len(devices))
            if self._head_key is not None:
                self._head_credits += len(devices)
        self._policy.charge(exp.name, len(devices) * elapsed, weight_of(exp))
        mf = self._mf()
        if (
            mf is not None
            and self.metrics_registry is not None
            and mf.applies(exp.spec)
        ):
            # per-stint device-seconds attribution: every rung stint of a
            # multi-fidelity sweep charges its gang here, so the bench's
            # ASHA-vs-flat comparison reads straight off /metrics
            self.metrics_registry.inc(
                "katib_multifidelity_device_seconds",
                value=round(len(devices) * elapsed, 6),
                experiment=exp.name,
            )
        if self.step_stats is not None:
            # objective-per-device-second rollup (ISSUE 20 satellite): every
            # gang release charges its device-seconds, multi-fidelity or not
            self.step_stats.charge_device_seconds(exp.name, len(devices) * elapsed)
        self.allocator.release(devices)

    def _note_checkpoint(self, trial_name: str) -> None:
        """ctx.checkpoint_store() save hook: victim selection prefers
        recently-checkpointed trials, and a preempted trial resumes (keeps
        its observation log) only if it checkpointed at all."""
        with self._lock:
            self._last_checkpoint[trial_name] = time.time()

    def _convert_backend_loss(
        self, trial: Trial, result: ExecutionResult, devices: Sequence[Any]
    ) -> ExecutionResult:
        """Device-loss-as-preemption (controller/deviceplane.py): a FAILED
        result whose traceback carries a backend-death signature
        (XlaRuntimeError and friends) means the DEVICES died, not the
        trial's code. The gang's devices are marked lost in the plane (they
        never return to the pool — and their disappearance can trigger
        failover), and the result converts to PREEMPTED so the standard
        requeue machinery resumes the trial on surviving devices from its
        last checkpoint (or re-runs it clean). No plane, or no signature
        match: the result passes through untouched."""
        from . import deviceplane

        dp = self._dp()
        if (
            dp is None
            or result.outcome != TrialOutcome.FAILED
            or not deviceplane.is_backend_loss(result.message)
            or not dp.report_executor_failure(trial.name, devices)
        ):
            return result
        with self._lock:
            self._preempting.add(trial.name)
        log.warning(
            "trial %s failed with a backend-death signature; converting to "
            "a device-loss preemption", trial.name,
        )
        return ExecutionResult(
            TrialOutcome.PREEMPTED,
            "backend error under the program (device loss); converted to a "
            "checkpoint-preemption: " + (result.message or "").strip()[-200:],
        )

    def _convert_pack_backend_loss(
        self,
        pack_id: str,
        trials: List[Trial],
        results: List[ExecutionResult],
        devices: Sequence[Any],
    ) -> List[ExecutionResult]:
        """Pack counterpart of _convert_backend_loss: one shared program,
        so one backend-death signature marks the whole gang's devices lost
        and every member failed by it converts to a preemption (members
        with their own outcome — killed, early-stopped — keep it)."""
        from . import deviceplane

        dp = self._dp()
        if dp is None:
            return results
        struck = [
            i
            for i, r in enumerate(results)
            if r.outcome == TrialOutcome.FAILED
            and deviceplane.is_backend_loss(r.message)
        ]
        if not struck or not dp.report_executor_failure(pack_id, devices):
            return results
        with self._lock:
            self._preempting.update(trials[i].name for i in struck)
        log.warning(
            "pack %s failed with a backend-death signature; converting %d "
            "member(s) to device-loss preemptions", pack_id, len(struck),
        )
        out = list(results)
        for i in struck:
            out[i] = ExecutionResult(
                TrialOutcome.PREEMPTED,
                "backend error under the shared program (device loss); "
                "converted to a checkpoint-preemption: "
                + (results[i].message or "").strip()[-200:],
            )
        return out

    def _preempt_applies(self, trial: Trial, result: ExecutionResult) -> bool:
        """Did this trial end because the fair-share policy preempted it?
        PREEMPTED is the cooperative exit; KILLED covers the grace-window
        escalation. A deliberate kill() or a controller shutdown always wins
        over a pending preemption, and a timeout (FAILED) stays a failure."""
        if self._shutdown.is_set():
            return False
        with self._lock:
            signaled = trial.name in self._preempting
            deliberate = trial.name in self._intentional_kills
        return (
            signaled
            and not deliberate
            and result.outcome in (TrialOutcome.PREEMPTED, TrialOutcome.KILLED)
        )

    def _requeue_preempted(self, exp: Experiment, trial: Trial) -> bool:
        """Requeue a preempted trial as resumable: PENDING again, back of
        the fair-share queue (its lower priority keeps it behind the gang
        that preempted it). With a checkpoint on record the observation log
        is KEPT — the resumed run continues reporting where it stopped, so
        the folded metrics are bit-identical to an unpreempted run; without
        one the re-run starts from scratch and the log is dropped (the same
        invariant as restart requeues)."""
        with self._lock:
            self._preempting.discard(trial.name)
            has_checkpoint = trial.name in self._last_checkpoint
        # the cooperative exit already ran the reporter's flush barrier; this
        # covers the grace-window kill escalation, where the victim's last
        # report predates the preempt signal and may still sit in the buffer
        self.obs_store.flush()
        if not has_checkpoint:
            self.obs_store.delete_observation_log(trial.name)
        trial.set_condition(
            TrialCondition.PENDING,
            "TrialPreempted",
            "preempted by higher-priority work; requeued"
            + (" (resumes from checkpoint)" if has_checkpoint else ""),
        )
        self.state.update_trial(trial)
        if self.metrics_registry is not None:
            self.metrics_registry.inc(
                "katib_trial_preempted_total", experiment=exp.name
            )
        if self.recorder is not None:
            self.recorder.event(
                exp.name, "Trial", trial.name, "TrialPreempted",
                "trial preempted by higher-priority work and requeued"
                + (" (resumes from checkpoint)" if has_checkpoint else ""),
            )
        with self._lock:
            self._stamp_enqueue(exp, trial)
            self._waiting.append((exp, trial))
        return True

    def forget_experiment(self, name: str) -> None:
        """Drop a deleted experiment's fair-share ledger + quota attribution
        so a future namesake starts with a clean share."""
        self._policy.forget(name)
        with self._lock:
            self._usage.pop(name, None)

    def queue_state(self) -> Dict[str, Any]:
        """Observable queue snapshot for /api/queue and the CLI: pending
        trials with priority / wait / fair-share deficit, running units, and
        the device pool."""
        from . import fairshare as fs

        now = time.time()
        dp = self._dp()
        with self._lock:
            waiting = list(self._waiting)
            running = list(self._running.values())
            enq = dict(self._enqueued_at)
            # the plane's count is live (zombie leases leave it when
            # reclaimed); the legacy counter only drops on thread exit
            quarantined = (
                dp.zombie_device_count() if dp is not None else self._quarantined
            )
            usage = dict(self._usage)
        deficits = self._policy.deficits(sorted({exp.name for exp, _ in waiting}))
        pending = []
        for exp, t in waiting:
            enqueued = enq.get(t.name, now)
            prio = fs.priority_of(exp)
            pending.append(
                {
                    "trial": t.name,
                    "experiment": exp.name,
                    "priorityClass": exp.spec.priority_class or "default",
                    "priority": prio,
                    "effectivePriority": round(
                        self._policy.effective_priority(prio, enqueued, now), 3
                    ),
                    "waitSeconds": round(max(now - enqueued, 0.0), 3),
                    "numDevices": max(exp.spec.trial_template.resources.num_devices, 1),
                    "deviceQuota": fs.device_quota_of(exp),
                    "fairShareDeficit": round(deficits.get(exp.name, 0.0), 3),
                }
            )
        pending.sort(key=lambda p: (-p["effectivePriority"], -p["waitSeconds"]))
        devices_view: Dict[str, Any] = {
            "total": self.allocator.total,
            "free": self.allocator.free_count,
            "quarantined": quarantined,
            "usageByExperiment": usage,
        }
        if dp is not None:
            devices_view["backend"] = dp.backend
            devices_view["lostTotal"] = dp.snapshot()["lostTotal"]
        return {
            "devices": devices_view,
            "pending": pending,
            "running": [
                {
                    "unit": u.key,
                    "experiment": u.experiment,
                    "trials": list(u.trial_names),
                    "devices": u.n_devices,
                    "priority": u.priority,
                    "preempting": u.preempt_signaled,
                    "runningSeconds": round(now - u.started, 3),
                }
                for u in running
            ],
        }

    @property
    def quarantined_count(self) -> int:
        dp = self._dp()
        if dp is not None:
            return dp.zombie_device_count()
        with self._lock:
            return self._quarantined

    def _maybe_restart(self, exp: Experiment, trial: Trial, result: ExecutionResult) -> bool:
        """Retry failed trials up to KatibConfig max_trial_restarts times
        (the reference leaves retries to the trial job's backoffLimit)."""
        if result.outcome != TrialOutcome.FAILED or not self.max_trial_restarts:
            return False
        with self._lock:
            attempts = self._restarts.get(trial.name, 0)
            if attempts >= self.max_trial_restarts:
                return False
            self._restarts[trial.name] = attempts + 1
        # drop the failed attempt's metrics so the next attempt's fold (and
        # its success/failure-condition classification) can't mix two
        # executions — same invariant as the requeue path in experiment.py
        self.obs_store.delete_observation_log(trial.name)
        trial.set_condition(
            TrialCondition.PENDING,
            "TrialRestarting",
            f"retry {attempts + 1}/{self.max_trial_restarts}: {result.message}",
        )
        self.state.update_trial(trial)
        with self._lock:
            self._stamp_enqueue(exp, trial)
            self._waiting.append((exp, trial))
        return True

    def _build_context(
        self, exp: Experiment, trial: Trial, devices, handle: Optional[TrialExecution] = None
    ) -> TrialContext:
        spec = exp.spec
        monitor = None
        if trial.early_stopping_rules:
            monitor = EarlyStoppingMonitor(
                trial.early_stopping_rules,
                spec.objective.objective_metric_name,
                spec.objective.type,
            )
        reporter = MetricsReporter(
            store=self.obs_store,
            trial_name=trial.name,
            monitor=monitor,
            kill_event=handle.kill_event if handle is not None else None,
            preempt_event=handle.preempt_event if handle is not None else None,
        )
        workdir = None
        if self.workdir_root:
            import os

            workdir = os.path.join(self.workdir_root, exp.name, trial.name)
            os.makedirs(workdir, exist_ok=True)
        tm = self._tm()
        compiled = None
        cs = self._cs()
        if cs is not None:
            # warm handoff: the AOT-compiled executable for this trial's
            # dispatch group (None when cold/evicted — the trial then
            # compiles inline and the persistent XLA cache still applies)
            try:
                compiled = cs.warm_executable_for(exp.spec, trial)
            except Exception:
                compiled = None
        return TrialContext(
            trial_name=trial.name,
            experiment_name=exp.name,
            assignments=trial.assignments_dict(),
            reporter=reporter,
            workdir=workdir,
            checkpoint_dir=self._checkpoint_dirs.get(trial.name),
            devices=list(devices),
            labels=dict(trial.labels),
            topology=spec.trial_template.resources.topology,
            on_checkpoint=lambda step, _t=trial.name: self._note_checkpoint(_t),
            # telemetry hooks (None when off — ctx.report pays one check):
            # every report is a watchdog heartbeat AND a device-lease
            # heartbeat; subprocess executors re-point /proc sampling at
            # the child pids they spawn
            on_report=self._report_heartbeat_hook([trial.name], trial.name),
            on_subprocess=(
                (lambda pids, _t=trial.name, _tm=tm: _tm.set_pids(_t, pids))
                if tm is not None else None
            ),
            compiled_program=compiled,
            step_clock=(
                self.step_stats.clock_for()
                if self.step_stats is not None else None
            ),
        )

    CONDITION_STDOUT_TAIL = 65536  # bytes of stdout offered to conditions

    def _apply_conditions(
        self, exp: Experiment, result: ExecutionResult, observation
    ) -> ExecutionResult:
        """Trial-defined success/failure predicates over terminal state
        (controller/conditions.py; reference job_util.go:59-120 — failure
        checked first, then success, else the default classification)."""
        template = exp.spec.trial_template
        if not (template.success_condition or template.failure_condition):
            return result
        if result.outcome not in (TrialOutcome.COMPLETED, TrialOutcome.FAILED):
            return result  # killed / early-stopped are controller-initiated
        from .conditions import ConditionError, evaluate_condition

        metrics: Dict[str, float] = {}
        for m in observation.metrics:
            if m.latest != UNAVAILABLE_METRIC_VALUE:
                try:
                    metrics[m.name] = float(m.latest)
                except ValueError:
                    pass
        stdout = ""
        if result.stdout_path:
            try:
                with open(result.stdout_path, "rb") as f:
                    f.seek(0, 2)
                    f.seek(max(0, f.tell() - self.CONDITION_STDOUT_TAIL))
                    stdout = f.read().decode(errors="replace")
            except OSError:
                pass
        state = dict(
            exit_code=result.exit_code,
            outcome=result.outcome.value,
            metrics=metrics,
            stdout=stdout,
        )
        if template.failure_condition:
            try:
                if evaluate_condition(template.failure_condition, **state):
                    return ExecutionResult(
                        TrialOutcome.FAILED,
                        f"failure condition met: {template.failure_condition}",
                        exit_code=result.exit_code,
                        stdout_path=result.stdout_path,
                    )
            except ConditionError as e:
                log.warning("trial failure condition error: %s", e)
        if template.success_condition:
            try:
                met = evaluate_condition(template.success_condition, **state)
            except ConditionError as e:
                met = False
                log.warning("trial success condition error: %s", e)
            if met:
                return ExecutionResult(
                    TrialOutcome.COMPLETED,
                    f"success condition met: {template.success_condition}",
                    exit_code=result.exit_code,
                    stdout_path=result.stdout_path,
                )
            # a finished process produces no further state, so an unmet
            # success condition is terminal failure (job_util.go would keep
            # a job Running awaiting more conditions; see conditions.py)
            msg = f"success condition not met: {template.success_condition}"
            if result.message:
                msg += f" ({result.message})"
            return ExecutionResult(
                TrialOutcome.FAILED,
                msg,
                exit_code=result.exit_code,
                stdout_path=result.stdout_path,
            )
        return result

    def _classify(self, exp: Experiment, trial: Trial, result: ExecutionResult):
        """Fold the observation log and apply trial success/failure
        conditions; returns the (possibly re-classified) result plus the
        folded observation. Runs before the restart decision in _run_trial.
        Answered from the store's incremental fold index (O(metrics));
        stores without one fall back to the full-log rescan."""
        observation = self.obs_store.folded(
            trial.name, exp.spec.objective.all_metric_names()
        )
        trial.observation = observation
        return self._apply_conditions(exp, result, observation), observation

    def _finalize(
        self, exp: Experiment, trial: Trial, result: ExecutionResult, observation
    ) -> None:
        """Terminal-condition bookkeeping for a trial whose result has
        already been classified by _classify (the single classification
        point); mirrors trial_controller_util.go:42-122."""
        spec = exp.spec
        obj_metric = observation.metric(spec.objective.objective_metric_name)
        # "available" deliberately accepts NON-numeric latest values: the
        # reference's darts flow collects a string objective
        # (examples/v1beta1/nas/darts-cpu.yaml objectiveMetricName
        # Best-Genotype, custom filter "(Genotype.*)") and such trials
        # Succeed. Numeric garbage can't arrive via the push SDK
        # (validate_metric_value raises, failing the trial) or the TEXT
        # default filter (numeric regex); a custom filter admitting strings
        # is, as in the reference, the experiment author's declaration that
        # the objective isn't rankable.
        metrics_available = (
            obj_metric is not None and obj_metric.latest != UNAVAILABLE_METRIC_VALUE
        )
        if self.step_stats is not None and metrics_available:
            # best-objective tracking for the per-device-second rollup;
            # non-numeric objectives (custom string collectors) are skipped
            try:
                self.step_stats.note_objective(
                    exp.name, float(obj_metric.latest),
                    spec.objective.type == ObjectiveType.MAXIMIZE,
                )
            except (TypeError, ValueError):
                pass

        if result.outcome == TrialOutcome.EARLY_STOPPED:
            trial.set_condition(
                TrialCondition.EARLY_STOPPED, "TrialEarlyStopped", "Trial is early stopped"
            )
        elif result.outcome == TrialOutcome.KILLED:
            with self._lock:
                deliberate = trial.name in self._intentional_kills
            if self._shutdown.is_set() and not deliberate:
                trial.set_condition(
                    TrialCondition.KILLED, "SchedulerShutdown",
                    "controller shutdown while trial was running",
                )
            else:
                trial.set_condition(TrialCondition.KILLED, "TrialKilled", result.message)
        elif result.outcome == TrialOutcome.FAILED:
            trial.set_condition(TrialCondition.FAILED, "TrialFailed", result.message)
        elif not metrics_available and spec.metrics_collector_spec.collector_kind != CollectorKind.NONE:
            trial.set_condition(
                TrialCondition.METRICS_UNAVAILABLE,
                "MetricsUnavailable",
                "Metrics are not available",
            )
        else:
            trial.set_condition(TrialCondition.SUCCEEDED, "TrialSucceeded", "Trial has succeeded")
        self._record_terminal(exp, trial)

    def _record_terminal(self, exp: Experiment, trial: Trial) -> None:
        """Terminal bookkeeping shared by every path that sets a trial's
        final condition (_finalize and _reuse_duplicate): persist, count,
        record the event, apply retainRun workdir semantics."""
        if self.journal is not None:
            # write-ahead: the journal carries the terminal condition before
            # the state store does, so a crash between the two replays to
            # "finished" instead of re-running a completed trial
            self.journal.append(
                "terminal", exp.name, trial=trial.name,
                condition=trial.condition.value,
                reason=trial.current_reason,
            )
        self.state.update_trial(trial)
        if self.suggestion_prefetch is not None:
            # fire-and-forget: the hook only enqueues a precompute job
            try:
                self.suggestion_prefetch(exp.name)
            except Exception:
                log.debug("suggestion prefetch hook failed", exc_info=True)
        if self.metrics_registry is not None:
            bucket = {
                TrialCondition.SUCCEEDED: "succeeded",
                TrialCondition.FAILED: "failed",
                TrialCondition.KILLED: "killed",
                TrialCondition.EARLY_STOPPED: "early_stopped",
                TrialCondition.METRICS_UNAVAILABLE: "metrics_unavailable",
            }.get(trial.condition, "completed")
            self.metrics_registry.inc(f"katib_trial_{bucket}_total", experiment=exp.name)
        if self.recorder is not None:
            warning = trial.condition in (TrialCondition.FAILED, TrialCondition.METRICS_UNAVAILABLE)
            self.recorder.event(
                exp.name, "Trial", trial.name,
                trial.current_reason or trial.condition.value,
                trial.message, warning=warning,
            )
        # retainRun semantics (trial_controller.go:297 deletes the finished
        # job unless retain): clean the workdir of successfully-finished
        # trials; failed/killed/metrics-unavailable workdirs are always kept
        # for postmortem (a deviation the reference can't offer — its pods
        # are gone either way).
        from .multifidelity import PAUSED_LABEL

        if (
            not exp.spec.trial_template.retain
            and self.workdir_root
            and trial.condition in (TrialCondition.SUCCEEDED, TrialCondition.EARLY_STOPPED)
            # a rung-paused trial's workdir holds the checkpoint its
            # promotion will resume from — never clean it while paused
            and PAUSED_LABEL not in trial.labels
        ):
            import os
            import shutil

            shutil.rmtree(
                os.path.join(self.workdir_root, exp.name, trial.name),
                ignore_errors=True,
            )
