"""katib_tpu — a TPU-native AutoML framework.

Hyperparameter tuning, early stopping, and neural architecture search with the
capability surface of kubeflow/katib, rebuilt idiomatically on JAX/XLA:
Experiment/Suggestion/Trial state machines over a local state store, an
in-process pluggable suggestion engine, a pjit/shard_map trial runtime that
gang-schedules JAX training onto TPU device meshes, push-based metric
observation logs, and orbax checkpointing for PBT lineage and resume.

See SURVEY.md for the structural map of the reference this matches.
"""

__version__ = "0.1.0"

from .api import (  # noqa: F401
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    TrialTemplate,
)
from .runtime.metrics import report_metrics  # noqa: F401  (SDK push API)


def __getattr__(name):
    # Lazy imports keep `import katib_tpu` light (no JAX/flax import cost
    # until a client or controller is actually used).
    if name == "KatibClient":
        from .client.katib_client import KatibClient

        return KatibClient
    if name == "search":
        from .client import search

        return search
    if name == "ExperimentController":
        from .controller.experiment import ExperimentController

        return ExperimentController
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
