"""Experiment validation — mirrors the validating webhook.

reference pkg/webhook/v1beta1/experiment/validator/validator.go:81-590.
Errors are accumulated (field.ErrorList style) and raised as one
ValidationError listing every problem.
"""

from __future__ import annotations

import re
from typing import List, Optional

from .spec import (
    CollectorKind,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveType,
    ParameterType,
    ResumePolicy,
)
from .status import Experiment, ExperimentReason

NAME_RE = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")

# Template placeholder syntax, reference consts/const.go:130-148.
TRIAL_PARAM_RE = re.compile(r"\$\{trialParameters\.([^}]+)\}")
META_PARAM_RE = re.compile(r"\$\{trialSpec\.([^}]+)\}")
META_KEYS = {"Name", "Namespace", "Kind", "APIVersion"}


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def validate_experiment(
    spec: ExperimentSpec,
    old: Optional[Experiment] = None,
    known_algorithms: Optional[set] = None,
    known_early_stopping: Optional[set] = None,
) -> None:
    """Raise ValidationError unless the spec is valid.

    Mirrors DefaultValidator.ValidateExperiment (validator.go:81-180); ``old``
    enables the restart-edit rules (only budgets editable; restart only from a
    restartable completed state — status_util.go:240-246).
    """
    errs: List[str] = []

    if not NAME_RE.match(spec.name or ""):
        errs.append(
            f"name {spec.name!r} must consist of lower case alphanumeric characters or '-', "
            "start with an alphabetic character, and end with an alphanumeric character"
        )

    if spec.max_failed_trial_count is not None and spec.max_failed_trial_count < 0:
        errs.append("maxFailedTrialCount should not be less than 0")
    if spec.max_trial_count is not None and spec.max_trial_count <= 0:
        errs.append("maxTrialCount must be greater than 0")
    if spec.parallel_trial_count is not None and spec.parallel_trial_count <= 0:
        errs.append("parallelTrialCount must be greater than 0")
    if (
        spec.max_failed_trial_count is not None
        and spec.max_trial_count is not None
        and spec.max_failed_trial_count > spec.max_trial_count
    ):
        errs.append("maxFailedTrialCount should be less than or equal to maxTrialCount")
    if (
        spec.parallel_trial_count is not None
        and spec.max_trial_count is not None
        and spec.parallel_trial_count > spec.max_trial_count
    ):
        errs.append("parallelTrialCount should be less than or equal to maxTrialCount")
    if spec.reuse_duplicate_results and spec.max_trial_count is None:
        # duplicate trials finalize synchronously inside submit(): without a
        # trial budget, an exhausted discrete space + unreachable goal would
        # spin the reconcile loop creating reused trials at CPU speed
        errs.append("reuseDuplicateResults requires maxTrialCount to bound the experiment")

    if old is not None:
        _validate_restart(spec, old, errs)

    _validate_objective(spec, errs)
    _validate_algorithm(spec, known_algorithms, errs)
    _validate_early_stopping(spec, known_early_stopping, errs)

    if spec.resume_policy not in (ResumePolicy.NEVER, ResumePolicy.LONG_RUNNING, ResumePolicy.FROM_VOLUME):
        errs.append(f"invalid resumePolicy {spec.resume_policy!r}")

    _validate_fairshare(spec, errs)

    _validate_trial_template(spec, errs)

    if not spec.parameters and spec.nas_config is None:
        errs.append("spec.parameters or spec.nasConfig must be specified")
    if spec.parameters and spec.nas_config is not None:
        errs.append("only one of spec.parameters and spec.nasConfig can be specified")
    if spec.parameters:
        _validate_parameters(spec.parameters, errs)

    _validate_metrics_collector(spec, errs)

    if errs:
        raise ValidationError(errs)


def _validate_restart(spec: ExperimentSpec, old: Experiment, errs: List[str]) -> None:
    """reference validator.go:117-145 + status_util.go:240-246
    (IsCompletedExperimentRestartable: only MaxTrialsReached with LongRunning or
    FromVolume)."""
    old_spec = old.spec
    changed = spec.to_json() != old_spec.to_json()
    if not changed:
        return
    if old.status.is_completed:
        restartable = (
            old.status.is_succeeded
            and old.status.reason == ExperimentReason.MAX_TRIALS_REACHED
            and old_spec.resume_policy in (ResumePolicy.LONG_RUNNING, ResumePolicy.FROM_VOLUME)
        )
        if not restartable:
            errs.append(
                "experiment can be restarted only if it succeeded by reaching max trials "
                "and resumePolicy is LongRunning or FromVolume"
            )
    if spec.max_trial_count is not None and spec.max_trial_count <= old.status.trials:
        errs.append("maxTrialCount must be greater than status.trials count")
    # Only budgets are editable (validator.go:139-144).
    a, b = spec.to_dict(), old_spec.to_dict()
    for k in ("maxTrialCount", "maxFailedTrialCount", "parallelTrialCount"):
        a.pop(k, None)
        b.pop(k, None)
    if a != b:
        errs.append("only parallelTrialCount, maxTrialCount and maxFailedTrialCount are editable")


def _validate_fairshare(spec: ExperimentSpec, errs: List[str]) -> None:
    """Fair-share scheduling knobs (controller/fairshare.py): an unknown
    priority class or an unsatisfiable device quota must fail at admission,
    not silently degrade in the dispatch loop."""
    from ..controller.fairshare import PRIORITY_CLASSES

    if spec.priority_class and spec.priority_class not in PRIORITY_CLASSES:
        errs.append(
            f"unknown priorityClass {spec.priority_class!r} "
            f"(known: {sorted(c for c in PRIORITY_CLASSES if c)})"
        )
    if spec.fair_share_weight <= 0:
        errs.append("fairShareWeight must be greater than 0")
    quota = spec.trial_template.resources.device_quota
    if quota is not None:
        if quota < 1:
            errs.append("trialTemplate.resources.deviceQuota must be >= 1")
        elif quota < spec.trial_template.resources.num_devices:
            errs.append(
                f"trialTemplate.resources.deviceQuota ({quota}) is less than "
                f"numDevices ({spec.trial_template.resources.num_devices}); "
                "no trial could ever dispatch"
            )


def _validate_objective(spec: ExperimentSpec, errs: List[str]) -> None:
    obj = spec.objective
    if obj.type not in (ObjectiveType.MINIMIZE, ObjectiveType.MAXIMIZE):
        errs.append("objective.type must be minimize or maximize")
    if not obj.objective_metric_name:
        errs.append("objective.objectiveMetricName must be specified")
    if obj.objective_metric_name in obj.additional_metric_names:
        errs.append("objective.additionalMetricNames should not contain objectiveMetricName")
    # katib-tpu/perf/ is the step-statistics plane's reserved observation
    # namespace (runtime/stepstats.py): the folder ignores it BY NAME, so an
    # objective under it would fold nothing and every trial would finish
    # MetricsUnavailable — reject at admission instead
    from ..runtime.stepstats import PERF_PREFIX

    for name in [obj.objective_metric_name, *obj.additional_metric_names]:
        if name and name.startswith(PERF_PREFIX):
            errs.append(
                f"metric name {name!r} is under the reserved {PERF_PREFIX!r} "
                "namespace (step-statistics rows; never folded as objectives)"
            )


def _validate_algorithm(spec: ExperimentSpec, known: Optional[set], errs: List[str]) -> None:
    if not spec.algorithm.algorithm_name:
        errs.append("algorithm.algorithmName must be specified")
        return
    if known is not None and spec.algorithm.algorithm_name not in known:
        errs.append(f"unknown algorithm {spec.algorithm.algorithm_name!r} (registered: {sorted(known)})")


def _validate_early_stopping(spec: ExperimentSpec, known: Optional[set], errs: List[str]) -> None:
    es = spec.early_stopping
    if es is None:
        return
    if not es.algorithm_name:
        errs.append("earlyStopping.algorithmName must be specified")
        return
    if known is not None and es.algorithm_name not in known:
        errs.append(f"unknown early-stopping algorithm {es.algorithm_name!r}")


def _validate_parameters(parameters, errs: List[str]) -> None:
    """reference validator.go:254-291."""
    seen = set()
    for i, p in enumerate(parameters):
        if p.name in seen:
            errs.append(f"parameters[{i}]: duplicate parameter name {p.name!r}")
        seen.add(p.name)
        fs = p.feasible_space
        if fs == FeasibleSpace():
            errs.append(f"parameters[{i}].feasibleSpace must be specified")
            continue
        if p.parameter_type in (ParameterType.DOUBLE, ParameterType.INT):
            if fs.list:
                errs.append(
                    f"parameters[{i}]: feasibleSpace.list is not supported for parameterType {p.parameter_type.value}"
                )
            if not fs.max and not fs.min:
                errs.append(
                    f"parameters[{i}]: feasibleSpace.max or feasibleSpace.min must be specified "
                    f"for parameterType {p.parameter_type.value}"
                )
        elif p.parameter_type in (ParameterType.CATEGORICAL, ParameterType.DISCRETE):
            if fs.max or fs.min or fs.step:
                errs.append(
                    f"parameters[{i}]: feasibleSpace .max, .min and .step are not supported "
                    f"for parameterType {p.parameter_type.value}"
                )
            if not fs.list:
                errs.append(f"parameters[{i}]: feasibleSpace.list must be specified")
        else:
            errs.append(f"parameters[{i}]: parameterType {p.parameter_type.value!r} is not supported")


def _validate_trial_template(spec: ExperimentSpec, errs: List[str]) -> None:
    """reference validator.go:293-426: the template must reference every trial
    parameter, every trial parameter must resolve to a search-space parameter
    (or meta key), and no placeholder may be dangling."""
    t = spec.trial_template
    sources = [t.command is not None, t.entry_point is not None, t.function is not None]
    if sum(sources) == 0:
        errs.append("trialTemplate must define one of command, entryPoint or function")
        return
    if sum(sources) > 1:
        errs.append("trialTemplate must define exactly one of command, entryPoint or function")
        return

    # Trial parameter names must be unique; references must exist in the search
    # space (or be NAS outputs / meta keys).
    search_params = {p.name for p in spec.parameters}
    if spec.nas_config is not None:
        # NAS suggestions emit architecture + nn_config assignments
        # (reference enas/service.py emits these names; darts emits
        # algorithm-settings/search-space/num-layers).
        search_params |= {
            "architecture",
            "nn_config",
            "algorithm-settings",
            "search-space",
            "num-layers",
        }
    tp_names = set()
    for tp in t.trial_parameters:
        if tp.name in tp_names:
            errs.append(f"trialParameters: duplicate name {tp.name!r}")
        tp_names.add(tp.name)
        if not tp.reference:
            errs.append(f"trialParameters[{tp.name}]: reference must be specified")
        elif tp.reference not in search_params and not _is_meta_key(tp.reference):
            errs.append(
                f"trialParameters[{tp.name}]: reference {tp.reference!r} not found in search space"
            )

    if t.resources.num_hosts < 1:
        errs.append("trialTemplate.resources.numHosts must be >= 1")
    if t.resources.pack_size < 1:
        errs.append("trialTemplate.resources.packSize must be >= 1")
    elif t.resources.pack_size > 1:
        # packing vmaps an in-process train loop over the member population;
        # a subprocess has nothing to vmap and a multi-host gang already owns
        # its own process group (controller/packing.py packability rules)
        if t.command is not None:
            errs.append(
                "trialTemplate.resources.packSize > 1 requires an in-process "
                "template (entryPoint or function) — command templates run "
                "as subprocesses and cannot be vmapped"
            )
        if t.resources.num_hosts > 1:
            errs.append(
                "trialTemplate.resources.packSize > 1 is incompatible with "
                "numHosts > 1"
            )
    if t.resources.topology:
        dims = t.resources.topology_dims()
        if dims is None:
            errs.append(
                f"trialTemplate.resources.topology {t.resources.topology!r} "
                "must be 'AxB[xC...]' positive integers"
            )
        else:
            import math as _math

            if _math.prod(dims) != t.resources.num_devices:
                errs.append(
                    f"trialTemplate.resources.topology {t.resources.topology!r} "
                    f"multiplies to {_math.prod(dims)}, but numDevices is "
                    f"{t.resources.num_devices}"
                )
    if t.resources.num_hosts > 1 and t.function is not None:
        errs.append(
            "trialTemplate.resources.numHosts > 1 requires a command or "
            "entryPoint template (an in-memory function cannot be "
            "distributed across worker processes)"
        )

    # success/failure condition expressions must parse and reference only the
    # trial terminal-state names (controller/conditions.py; the reference
    # validates its GJSON success/failure conditions in validator.go)
    from ..controller.conditions import ConditionError, parse_condition

    for cond_field, expr in (
        ("successCondition", t.success_condition),
        ("failureCondition", t.failure_condition),
    ):
        if expr:
            try:
                tree = parse_condition(expr)
            except ConditionError as e:
                errs.append(f"trialTemplate.{cond_field}: {e}")
                continue
            if t.command is None and t.resources.num_hosts <= 1:
                # truly in-process trials capture no stdout — a stdout-based
                # condition would silently never match. Multi-host entryPoint
                # gangs DO capture stdout (MultiHostExecutor writes the
                # primary's to host-0/stdout.log), so they are exempt.
                import ast as _ast

                if any(
                    isinstance(n, _ast.Name) and n.id == "stdout"
                    for n in _ast.walk(tree)
                ):
                    errs.append(
                        f"trialTemplate.{cond_field}: 'stdout' is only "
                        "available for command templates (in-process trials "
                        "capture no stdout)"
                    )

    if t.command is not None:
        text = "\n".join(t.command)
        used = set(TRIAL_PARAM_RE.findall(text))
        for name in used - tp_names:
            errs.append(f"template placeholder ${{trialParameters.{name}}} has no trialParameters entry")
        for name in tp_names - used:
            errs.append(f"trialParameters[{name}] is not used in the template")
        for meta in META_PARAM_RE.findall(text):
            base = meta.split("[", 1)[0]
            if base not in META_KEYS and not meta.startswith(("Annotations[", "Labels[")):
                errs.append(f"unknown trialSpec meta placeholder ${{trialSpec.{meta}}}")


# Semantic admission pre-flight (analysis/program.py, ISSUE 7): fraction of
# device memory above which the predicted peak earns a warning event even
# though the experiment is admitted.
HBM_WARN_FRACTION = 0.8


def predicted_memory_errors(
    peak_bytes: int, capacity_bytes: int, target: str
) -> List[str]:
    """Admission check over the jaxpr-level cost model's peak-HBM estimate
    — a *lower bound* on what XLA will allocate, so exceeding capacity is a
    certain OOM, not a maybe (the PR 5 watchdog catches the runtime rest).
    Returns field-error strings in the validator's accumulate style."""
    if capacity_bytes and peak_bytes > capacity_bytes:
        return [
            f"trialTemplate: predicted peak HBM of {peak_bytes} bytes for "
            f"{target} exceeds device memory ({capacity_bytes} bytes); the "
            "trial cannot fit — shrink the model/batch corners of the "
            "search space or request a larger slice "
            "(estimate: katib-tpu analyze)"
        ]
    return []


def predicted_memory_warning(
    peak_bytes: int, capacity_bytes: int, target: str
) -> Optional[str]:
    """Near-capacity warning text (>= HBM_WARN_FRACTION of the device),
    emitted as a PredictedHbmNearCapacity event by the controller."""
    if capacity_bytes and peak_bytes > capacity_bytes * HBM_WARN_FRACTION:
        return (
            f"predicted peak HBM {peak_bytes} bytes for {target} is within "
            f"{100 * (1 - HBM_WARN_FRACTION):.0f}% of device memory "
            f"({capacity_bytes} bytes); the static estimate is a lower "
            "bound — XLA temporaries may push the trial over"
        )
    return None


def _is_meta_key(reference: str) -> bool:
    """reference validator.go:564-581 (isMetaKey)."""
    if reference in {f"${{trialSpec.{k}}}" for k in META_KEYS}:
        return True
    return bool(re.match(r"^\$\{trialSpec\.(Annotations|Labels)\[[^\]]+\]\}$", reference))


def _validate_metrics_collector(spec: ExperimentSpec, errs: List[str]) -> None:
    """reference validator.go:475-562 (subset without K8s container checks)."""
    mc = spec.metrics_collector_spec
    if mc.collector_kind in (CollectorKind.FILE, CollectorKind.TF_EVENT):
        if mc.source is None or not mc.source.file_path:
            errs.append(f"metricsCollector kind {mc.collector_kind.value} requires source.filePath")
    if mc.custom_command is not None:
        if mc.collector_kind != CollectorKind.CUSTOM:
            errs.append("customCollector.command requires collector kind Custom")
        elif not (
            isinstance(mc.custom_command, list)
            and mc.custom_command
            and all(isinstance(a, str) for a in mc.custom_command)
        ):
            errs.append("customCollector.command must be a non-empty list of strings")
    elif mc.collector_kind == CollectorKind.CUSTOM:
        # symmetric requirement (reference: a Custom collector must define its
        # container, common_types.go:205-227) — otherwise the user's collector
        # silently never runs and metrics come from the wrong source
        errs.append("collector kind Custom requires customCollector.command")
    if mc.collector_kind == CollectorKind.FILE and mc.source and mc.source.filter:
        for f in mc.source.filter.metrics_format:
            try:
                ngroups = re.compile(f).groups
            except re.error:
                errs.append(f"metricsCollector filter {f!r} is not a valid regex")
                continue
            if ngroups != 2:
                errs.append(f"metricsCollector filter {f!r} must have exactly 2 capture groups")
