"""Experiment defaulting — mirrors the mutating webhook.

reference pkg/apis/controller/experiments/v1beta1/experiment_defaults.go:27-178
and pkg/webhook/v1beta1/experiment/mutate_webhook.go.
"""

from __future__ import annotations

from .spec import (
    CollectorKind,
    ExperimentSpec,
    MetricStrategy,
    MetricStrategyType,
    MetricsCollectorSpec,
    ObjectiveType,
    ResumePolicy,
    SourceSpec,
)

# reference experiment_defaults.go DefaultTrialParallelCount = 3
DEFAULT_PARALLEL_TRIAL_COUNT = 3
DEFAULT_RESUME_POLICY = ResumePolicy.NEVER
# reference common_types.go DefaultFilePath = "/var/log/katib/metrics.log";
# TPU-native: per-trial workdir-relative path.
DEFAULT_METRICS_FILE = "metrics.log"


def _default_strategy_for(objective_type: ObjectiveType) -> MetricStrategyType:
    if objective_type == ObjectiveType.MINIMIZE:
        return MetricStrategyType.MIN
    if objective_type == ObjectiveType.MAXIMIZE:
        return MetricStrategyType.MAX
    return MetricStrategyType.LATEST


def set_defaults(spec: ExperimentSpec, default_parallel: int = None) -> ExperimentSpec:
    """Fill all defaultable fields in place (and return the spec).

    Order follows Experiment.SetDefault (experiment_defaults.go:27-33):
    parallelTrialCount, resumePolicy, objective metric strategies,
    trial template conditions, metrics collector. ``default_parallel``
    overrides the built-in parallel-trial default (KatibConfig runtime).
    """
    if spec.parallel_trial_count is None:
        spec.parallel_trial_count = default_parallel or DEFAULT_PARALLEL_TRIAL_COUNT
    if not spec.resume_policy:
        spec.resume_policy = DEFAULT_RESUME_POLICY

    # Metric strategies: objective metric gets min/max by objective type, any
    # additional metric without an explicit strategy gets the same default
    # (experiment_defaults.go:48-95).
    obj = spec.objective
    existing = {s.name for s in obj.metric_strategies}
    if obj.objective_metric_name and obj.objective_metric_name not in existing:
        obj.metric_strategies.append(
            MetricStrategy(name=obj.objective_metric_name, value=_default_strategy_for(obj.type))
        )
    for metric in obj.additional_metric_names:
        if metric not in existing and metric != obj.objective_metric_name:
            obj.metric_strategies.append(
                MetricStrategy(name=metric, value=_default_strategy_for(obj.type))
            )

    # Metrics collector: the reference defaults to a StdOut scraping sidecar
    # (experiment_defaults.go:131-137). TPU-native default is PUSH for
    # in-process entry points; subprocess command trials default to STDOUT
    # scraping for parity with arbitrary training scripts.
    if spec.metrics_collector_spec is None:
        spec.metrics_collector_spec = MetricsCollectorSpec()
    mc = spec.metrics_collector_spec
    if mc.collector_kind == CollectorKind.PROMETHEUS and mc.source is None:
        # reference experiment_defaults.go: scrape defaults path=/metrics port=8080
        mc.source = SourceSpec()
    if mc.collector_kind in (CollectorKind.FILE, CollectorKind.TF_EVENT) and mc.source is None:
        mc.source = SourceSpec(file_path=DEFAULT_METRICS_FILE)
    # Subprocess trials (command templates, and multi-host gangs whose
    # workers are separate processes reporting via stdout) default to STDOUT
    # scraping for parity with arbitrary training scripts.
    is_subprocess_trial = (
        spec.trial_template.command is not None
        or spec.trial_template.resources.num_hosts > 1
    )
    if is_subprocess_trial and mc.collector_kind == CollectorKind.PUSH:
        mc.collector_kind = CollectorKind.STDOUT

    return spec
