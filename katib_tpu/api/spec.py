"""Core experiment/trial/suggestion specification types.

TPU-native re-design of Katib's CRD API surface. The reference defines these as
Kubernetes CRD Go structs; here they are plain dataclasses held in a local state
store (katib_tpu.db) instead of etcd, but the field semantics are preserved:

- ExperimentSpec / ExperimentStatus:
  reference pkg/apis/controller/experiments/v1beta1/experiment_types.go:26-324
- TrialSpec / TrialStatus:
  reference pkg/apis/controller/trials/v1beta1/trial_types.go:27-153
- SuggestionSpec / SuggestionStatus:
  reference pkg/apis/controller/suggestions/v1beta1/suggestion_types.go:29-150
- Objective / metrics-collector / algorithm common types:
  reference pkg/apis/controller/common/v1beta1/common_types.go:25-234

Instead of an unstructured Kubernetes runSpec, a trial's run spec is either a
shell command template (``${trialParameters.x}`` substitution, mirroring
pkg/controller.v1beta1/experiment/manifest/generator.go:99-186) or a Python
entry point resolved in-process (the TPU-native fast path used by
``KatibClient.tune``).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union


# ---------------------------------------------------------------------------
# Enums (reference: common_types.go, experiment_types.go)
# ---------------------------------------------------------------------------

class ObjectiveType(str, enum.Enum):
    """reference common_types.go:27-35 (ObjectiveTypeMinimize/Maximize)."""

    MINIMIZE = "minimize"
    MAXIMIZE = "maximize"
    UNKNOWN = ""


class MetricStrategyType(str, enum.Enum):
    """How to fold a metric's observation log into one value.

    reference common_types.go:58-64 (ExtractByMin/Max/Latest).
    """

    MIN = "min"
    MAX = "max"
    LATEST = "latest"


class ParameterType(str, enum.Enum):
    """reference experiment_types.go:197-204."""

    DOUBLE = "double"
    INT = "int"
    DISCRETE = "discrete"
    CATEGORICAL = "categorical"
    UNKNOWN = "unknown"


class Distribution(str, enum.Enum):
    """reference experiment_types.go:214-220."""

    UNIFORM = "uniform"
    LOG_UNIFORM = "logUniform"
    NORMAL = "normal"
    LOG_NORMAL = "logNormal"
    UNKNOWN = "unknown"


class ResumePolicy(str, enum.Enum):
    """reference experiment_types.go:179-191.

    NEVER: suggestion service state is dropped at completion; experiment cannot
        be resumed.
    LONG_RUNNING: suggestion state is kept in memory; experiment can be resumed
        by raising budgets.
    FROM_VOLUME: suggestion state is persisted (here: to the state-store
        directory rather than a PVC) and restorable after restart.
    """

    NEVER = "Never"
    LONG_RUNNING = "LongRunning"
    FROM_VOLUME = "FromVolume"


class CollectorKind(str, enum.Enum):
    """reference common_types.go:205-227."""

    STDOUT = "StdOut"
    FILE = "File"
    TF_EVENT = "TfEvent"
    PROMETHEUS = "PrometheusMetric"
    CUSTOM = "Custom"
    NONE = "None"
    PUSH = "Push"  # TPU-native first-class push reporting (katib_tpu.runtime.metrics)


class ComparisonType(str, enum.Enum):
    """reference common_types.go:118-129 (early stopping rule comparison)."""

    EQUAL = "equal"
    LESS = "less"
    GREATER = "greater"


# ---------------------------------------------------------------------------
# Search space
# ---------------------------------------------------------------------------

@dataclass
class FeasibleSpace:
    """reference experiment_types.go:222-232.

    min/max/step are strings in the reference (CRD round-tripping); we keep
    them as strings at the API boundary and parse in
    katib_tpu.suggest.internal.search_space.
    """

    min: Optional[str] = None
    max: Optional[str] = None
    list: Optional[List[str]] = None
    step: Optional[str] = None
    distribution: Optional[Distribution] = None

    def __post_init__(self):
        # accept plain strings ("logUniform") and numbers at the API boundary
        if self.distribution is not None and not isinstance(self.distribution, Distribution):
            self.distribution = Distribution(self.distribution)
        for f in ("min", "max", "step"):
            v = getattr(self, f)
            if v is not None and not isinstance(v, str):
                setattr(self, f, str(v))
        if self.list is not None:
            self.list = [str(x) for x in self.list]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.min is not None:
            d["min"] = self.min
        if self.max is not None:
            d["max"] = self.max
        if self.list is not None:
            d["list"] = list(self.list)
        if self.step is not None:
            d["step"] = self.step
        if self.distribution is not None:
            d["distribution"] = self.distribution.value
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FeasibleSpace":
        return cls(
            min=d.get("min"),
            max=d.get("max"),
            list=d.get("list"),
            step=d.get("step"),
            distribution=Distribution(d["distribution"]) if d.get("distribution") else None,
        )


@dataclass
class ParameterSpec:
    """reference experiment_types.go:191-195."""

    name: str
    parameter_type: ParameterType
    feasible_space: FeasibleSpace

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "parameterType": self.parameter_type.value,
            "feasibleSpace": self.feasible_space.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ParameterSpec":
        return cls(
            name=d["name"],
            parameter_type=ParameterType(d["parameterType"]),
            feasible_space=FeasibleSpace.from_dict(d.get("feasibleSpace", {})),
        )


# ---------------------------------------------------------------------------
# Objective / metrics
# ---------------------------------------------------------------------------

@dataclass
class MetricStrategy:
    """reference common_types.go:66-69."""

    name: str
    value: MetricStrategyType

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "value": self.value.value}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MetricStrategy":
        return cls(name=d["name"], value=MetricStrategyType(d["value"]))


@dataclass
class ObjectiveSpec:
    """reference common_types.go:37-56."""

    type: ObjectiveType = ObjectiveType.UNKNOWN
    goal: Optional[float] = None
    objective_metric_name: str = ""
    additional_metric_names: List[str] = field(default_factory=list)
    metric_strategies: List[MetricStrategy] = field(default_factory=list)

    def all_metric_names(self) -> List[str]:
        return [self.objective_metric_name] + list(self.additional_metric_names)

    def strategy_for(self, metric: str) -> MetricStrategyType:
        for s in self.metric_strategies:
            if s.name == metric:
                return s.value
        # default mirrors experiment_defaults.go setDefaultMetricStrategies:
        # maximize -> max, minimize -> min
        if self.type == ObjectiveType.MINIMIZE:
            return MetricStrategyType.MIN
        return MetricStrategyType.MAX

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "type": self.type.value,
            "objectiveMetricName": self.objective_metric_name,
        }
        if self.goal is not None:
            d["goal"] = self.goal
        if self.additional_metric_names:
            d["additionalMetricNames"] = list(self.additional_metric_names)
        if self.metric_strategies:
            d["metricStrategies"] = [s.to_dict() for s in self.metric_strategies]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectiveSpec":
        return cls(
            type=ObjectiveType(d.get("type", "")),
            goal=d.get("goal"),
            objective_metric_name=d.get("objectiveMetricName", ""),
            additional_metric_names=list(d.get("additionalMetricNames", [])),
            metric_strategies=[MetricStrategy.from_dict(s) for s in d.get("metricStrategies", [])],
        )


# ---------------------------------------------------------------------------
# Algorithm / early stopping
# ---------------------------------------------------------------------------

@dataclass
class AlgorithmSetting:
    """reference common_types.go:95-101."""

    name: str
    value: str

    def to_dict(self) -> Dict[str, str]:
        return {"name": self.name, "value": self.value}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlgorithmSetting":
        return cls(name=d["name"], value=str(d["value"]))


@dataclass
class AlgorithmSpec:
    """reference common_types.go:86-93."""

    algorithm_name: str = ""
    algorithm_settings: List[AlgorithmSetting] = field(default_factory=list)

    def settings_dict(self) -> Dict[str, str]:
        return {s.name: s.value for s in self.algorithm_settings}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "algorithmName": self.algorithm_name,
            "algorithmSettings": [s.to_dict() for s in self.algorithm_settings],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlgorithmSpec":
        return cls(
            algorithm_name=d.get("algorithmName", ""),
            algorithm_settings=[AlgorithmSetting.from_dict(s) for s in d.get("algorithmSettings", [])],
        )


@dataclass
class EarlyStoppingSpec:
    """reference common_types.go:103-110."""

    algorithm_name: str = ""
    algorithm_settings: List[AlgorithmSetting] = field(default_factory=list)

    def settings_dict(self) -> Dict[str, str]:
        return {s.name: s.value for s in self.algorithm_settings}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "algorithmName": self.algorithm_name,
            "algorithmSettings": [s.to_dict() for s in self.algorithm_settings],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EarlyStoppingSpec":
        return cls(
            algorithm_name=d.get("algorithmName", ""),
            algorithm_settings=[AlgorithmSetting.from_dict(s) for s in d.get("algorithmSettings", [])],
        )


@dataclass
class EarlyStoppingRule:
    """reference common_types.go:112-129 and api.proto EarlyStoppingRule."""

    name: str
    value: str
    comparison: ComparisonType
    start_step: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "value": self.value,
            "comparison": self.comparison.value,
            "startStep": self.start_step,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EarlyStoppingRule":
        return cls(
            name=d["name"],
            value=str(d["value"]),
            comparison=ComparisonType(d["comparison"]),
            start_step=int(d.get("startStep", 0)),
        )


# ---------------------------------------------------------------------------
# Metrics collector
# ---------------------------------------------------------------------------

@dataclass
class FilterSpec:
    """reference common_types.go:229-234 (metricsFormat regexes)."""

    metrics_format: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"metricsFormat": list(self.metrics_format)}


@dataclass
class SourceSpec:
    """reference common_types.go:154-203: file_system_path + filter, plus the
    PrometheusMetric httpGet source (host/port/path) scraped by the
    subprocess executor while the trial runs."""

    file_path: Optional[str] = None
    file_format: str = "TEXT"  # TEXT | JSON, reference common_types.go FileSystemKind
    filter: Optional[FilterSpec] = None
    http_host: str = "127.0.0.1"
    http_port: int = 8080   # reference experiment_defaults.go Prometheus case
    http_path: str = "/metrics"

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"fileFormat": self.file_format}
        if self.file_path:
            d["filePath"] = self.file_path
        if self.filter:
            d["filter"] = self.filter.to_dict()
        if (self.http_host, self.http_port, self.http_path) != ("127.0.0.1", 8080, "/metrics"):
            d["httpGet"] = {"host": self.http_host, "port": self.http_port, "path": self.http_path}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SourceSpec":
        filt = d.get("filter")
        http = d.get("httpGet") or {}
        return cls(
            file_path=d.get("filePath"),
            file_format=d.get("fileFormat", "TEXT"),
            filter=FilterSpec(metrics_format=list(filt.get("metricsFormat", []))) if filt else None,
            http_host=http.get("host", "127.0.0.1"),
            http_port=int(http.get("port", 8080)),
            http_path=http.get("path", "/metrics"),
        )


@dataclass
class MetricsCollectorSpec:
    """reference common_types.go:131-152; ``custom_command`` carries the
    Custom collector's user-supplied program (the reference's custom
    container spec, common_types.go:205-227): it runs after the trial exits,
    with KATIB_TRIAL_* env pointing at the trial workdir, and its stdout is
    parsed like a File collector."""

    collector_kind: CollectorKind = CollectorKind.PUSH
    source: Optional[SourceSpec] = None
    custom_command: Optional[List[str]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"collector": {"kind": self.collector_kind.value}}
        if self.custom_command:
            d["collector"]["customCollector"] = {"command": list(self.custom_command)}
        if self.source:
            d["source"] = self.source.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MetricsCollectorSpec":
        collector = d.get("collector", {})
        custom = collector.get("customCollector") or {}
        cmd = custom.get("command")
        if cmd is not None and not isinstance(cmd, (list, tuple)):
            raise ValueError(
                f"customCollector.command must be a list of strings, got {type(cmd).__name__}"
            )
        return cls(
            collector_kind=CollectorKind(collector.get("kind", "Push")),
            source=SourceSpec.from_dict(d["source"]) if d.get("source") else None,
            custom_command=list(cmd) if cmd else None,
        )


# ---------------------------------------------------------------------------
# NAS config
# ---------------------------------------------------------------------------

@dataclass
class NasOperation:
    """reference experiment_types.go:283-288 (Operation)."""

    operation_type: str
    parameters: List[ParameterSpec] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "operationType": self.operation_type,
            "parameters": [p.to_dict() for p in self.parameters],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NasOperation":
        return cls(
            operation_type=d["operationType"],
            parameters=[ParameterSpec.from_dict(p) for p in d.get("parameters", [])],
        )


@dataclass
class GraphConfig:
    """reference experiment_types.go:272-281."""

    num_layers: Optional[int] = None
    input_sizes: Optional[List[int]] = None
    output_sizes: Optional[List[int]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.num_layers is not None:
            d["numLayers"] = self.num_layers
        if self.input_sizes is not None:
            d["inputSizes"] = list(self.input_sizes)
        if self.output_sizes is not None:
            d["outputSizes"] = list(self.output_sizes)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GraphConfig":
        return cls(
            num_layers=d.get("numLayers"),
            input_sizes=d.get("inputSizes"),
            output_sizes=d.get("outputSizes"),
        )


@dataclass
class NasConfig:
    """reference experiment_types.go:264-270."""

    graph_config: GraphConfig = field(default_factory=GraphConfig)
    operations: List[NasOperation] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "graphConfig": self.graph_config.to_dict(),
            "operations": [o.to_dict() for o in self.operations],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NasConfig":
        return cls(
            graph_config=GraphConfig.from_dict(d.get("graphConfig", {})),
            operations=[NasOperation.from_dict(o) for o in d.get("operations", [])],
        )


# ---------------------------------------------------------------------------
# Trial template (TPU-native replacement for unstructured K8s runSpec)
# ---------------------------------------------------------------------------

def parse_topology(topology: Optional[str]) -> Optional[List[int]]:
    """Parse an "AxB[xC...]" topology string into dims; None when unset or
    malformed. The ONE parse rule shared by spec validation (which rejects
    malformed strings at admission) and the trial contexts (which treat
    malformed as absent — a worker env var bypasses admission)."""
    if not topology:
        return None
    try:
        dims = [int(d) for d in topology.lower().split("x")]
    except ValueError:
        return None
    return dims if all(d >= 1 for d in dims) else None

@dataclass
class TrialResources:
    """TPU slice request for one trial — replaces K8s resource requests.

    Katib delegates device placement to the trial CRD; here the scheduler
    gang-allocates TPU devices directly (SURVEY.md §7 layer 4).
    ``topology`` ("2x2", "4x2", ...) must multiply out to ``num_devices``
    (validated at admission) and becomes the default mesh shape of
    ``ctx.mesh()`` inside the trial.
    """

    num_devices: int = 1          # TPU chips (or virtual CPU devices in tests)
    num_hosts: int = 1            # multi-host slice width (DCN processes)
    topology: Optional[str] = None  # e.g. "2x2" — default ctx.mesh() shape
    # Vmapped trial packing (controller/packing.py): up to pack_size pending
    # in-process trials with identical templates and all-scalar assignments
    # share ONE device allocation and ONE compiled (vmap'ed) train loop.
    # 1 = no packing; requires an in-process single-host template.
    pack_size: int = 1
    # Fair-share scheduling (controller/fairshare.py): cap on devices this
    # experiment's trials may hold concurrently; None = unlimited. Must be
    # >= num_devices or no trial could ever dispatch (validated).
    device_quota: Optional[int] = None

    def topology_dims(self) -> Optional[List[int]]:
        return parse_topology(self.topology)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"numDevices": self.num_devices, "numHosts": self.num_hosts}
        if self.topology:
            d["topology"] = self.topology
        if self.pack_size != 1:
            d["packSize"] = self.pack_size
        if self.device_quota is not None:
            d["deviceQuota"] = self.device_quota
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialResources":
        return cls(
            num_devices=int(d.get("numDevices", 1)),
            num_hosts=int(d.get("numHosts", 1)),
            topology=d.get("topology"),
            pack_size=int(d.get("packSize", 1)),
            device_quota=(
                int(d["deviceQuota"]) if d.get("deviceQuota") is not None else None
            ),
        )


@dataclass
class TrialParameterSpec:
    """reference experiment_types.go:310-324 (TrialParameterSpec): maps a
    template placeholder name to a search-space parameter reference."""

    name: str
    description: str = ""
    reference: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "description": self.description, "reference": self.reference}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialParameterSpec":
        return cls(
            name=d["name"],
            description=d.get("description", ""),
            reference=d.get("reference", ""),
        )


@dataclass
class TrialTemplate:
    """TPU-native trial template — reference experiment_types.go:247-308.

    Exactly one of:
    - ``command``: argv template; ``${trialParameters.X}`` placeholders are
      substituted like manifest/generator.go:99-186. Runs as a subprocess.
    - ``entry_point``: "module:function" resolved in-process; called as
      fn(assignments_dict, trial_context). The TPU-native fast path (no
      process-per-trial overhead; the function runs under the trial's device
      mesh).
    - ``function``: a Python callable (not serializable; in-memory experiments
      and KatibClient.tune only).
    """

    command: Optional[List[str]] = None
    entry_point: Optional[str] = None
    function: Optional[Callable[..., Any]] = None
    trial_parameters: List[TrialParameterSpec] = field(default_factory=list)
    resources: TrialResources = field(default_factory=TrialResources)
    # reference experiment_types.go Retain (retainRun): keep the trial's
    # workdir (stdout/logs/profiles) after successful completion; without it
    # the scheduler cleans up like the trial controller deletes finished jobs
    # (trial_controller.go:297). Failed/killed workdirs are always kept for
    # postmortem.
    retain: bool = False
    success_condition: str = ""   # reference experiment_types.go:300-308 (GJSON in ref)
    failure_condition: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    working_dir: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "trialParameters": [p.to_dict() for p in self.trial_parameters],
            "resources": self.resources.to_dict(),
            "retain": self.retain,
        }
        if self.command is not None:
            d["command"] = list(self.command)
        if self.entry_point is not None:
            d["entryPoint"] = self.entry_point
        if self.env:
            d["env"] = dict(self.env)
        if self.working_dir:
            d["workingDir"] = self.working_dir
        if self.success_condition:
            d["successCondition"] = self.success_condition
        if self.failure_condition:
            d["failureCondition"] = self.failure_condition
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialTemplate":
        return cls(
            command=d.get("command"),
            entry_point=d.get("entryPoint"),
            trial_parameters=[TrialParameterSpec.from_dict(p) for p in d.get("trialParameters", [])],
            resources=TrialResources.from_dict(d.get("resources", {})),
            retain=bool(d.get("retain", False)),
            env=dict(d.get("env", {})),
            working_dir=d.get("workingDir"),
            success_condition=d.get("successCondition", ""),
            failure_condition=d.get("failureCondition", ""),
        )


# ---------------------------------------------------------------------------
# Experiment spec
# ---------------------------------------------------------------------------

@dataclass
class ExperimentSpec:
    """reference experiment_types.go:26-77 (ExperimentSpec)."""

    name: str = ""
    parameters: List[ParameterSpec] = field(default_factory=list)
    objective: ObjectiveSpec = field(default_factory=ObjectiveSpec)
    algorithm: AlgorithmSpec = field(default_factory=AlgorithmSpec)
    early_stopping: Optional[EarlyStoppingSpec] = None
    trial_template: TrialTemplate = field(default_factory=TrialTemplate)
    parallel_trial_count: Optional[int] = None
    max_trial_count: Optional[int] = None
    max_failed_trial_count: Optional[int] = None
    metrics_collector_spec: MetricsCollectorSpec = field(default_factory=MetricsCollectorSpec)
    nas_config: Optional[NasConfig] = None
    resume_policy: ResumePolicy = ResumePolicy.NEVER
    # TPU-first addition with no reference counterpart: when True, a new
    # trial whose parameter assignments exactly match an already-Succeeded
    # trial of the same experiment reuses that trial's observation log
    # instead of re-running the workload (opt-in — stochastic trials give
    # different metrics per run, so the author must declare determinism).
    # Trials carrying checkpoint lineage (PBT exploit/explore) never reuse.
    reuse_duplicate_results: bool = False
    # Fair-share scheduling (controller/fairshare.py): named priority class
    # ("low" | "default" | "high" | "urgent"; "" = default) inherited by this
    # experiment's trials, and the weight scaling its fair share of device
    # time across concurrent experiments. Defaults preserve FIFO dispatch.
    priority_class: str = ""
    fair_share_weight: float = 1.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "parameters": [p.to_dict() for p in self.parameters],
            "objective": self.objective.to_dict(),
            "algorithm": self.algorithm.to_dict(),
            "trialTemplate": self.trial_template.to_dict(),
            "metricsCollectorSpec": self.metrics_collector_spec.to_dict(),
            "resumePolicy": self.resume_policy.value,
        }
        if self.early_stopping:
            d["earlyStopping"] = self.early_stopping.to_dict()
        if self.parallel_trial_count is not None:
            d["parallelTrialCount"] = self.parallel_trial_count
        if self.max_trial_count is not None:
            d["maxTrialCount"] = self.max_trial_count
        if self.max_failed_trial_count is not None:
            d["maxFailedTrialCount"] = self.max_failed_trial_count
        if self.nas_config:
            d["nasConfig"] = self.nas_config.to_dict()
        if self.reuse_duplicate_results:
            d["reuseDuplicateResults"] = True
        if self.priority_class:
            d["priorityClass"] = self.priority_class
        if self.fair_share_weight != 1.0:
            d["fairShareWeight"] = self.fair_share_weight
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExperimentSpec":
        mc = d.get("metricsCollectorSpec")
        return cls(
            name=d.get("name", ""),
            parameters=[ParameterSpec.from_dict(p) for p in d.get("parameters", [])],
            objective=ObjectiveSpec.from_dict(d.get("objective", {})),
            algorithm=AlgorithmSpec.from_dict(d.get("algorithm", {})),
            early_stopping=EarlyStoppingSpec.from_dict(d["earlyStopping"]) if d.get("earlyStopping") else None,
            trial_template=TrialTemplate.from_dict(d.get("trialTemplate", {})),
            parallel_trial_count=d.get("parallelTrialCount"),
            max_trial_count=d.get("maxTrialCount"),
            max_failed_trial_count=d.get("maxFailedTrialCount"),
            metrics_collector_spec=(
                MetricsCollectorSpec.from_dict(mc) if mc else MetricsCollectorSpec()
            ),
            nas_config=NasConfig.from_dict(d["nasConfig"]) if d.get("nasConfig") else None,
            resume_policy=ResumePolicy(d.get("resumePolicy", "Never")),
            reuse_duplicate_results=bool(d.get("reuseDuplicateResults", False)),
            priority_class=d.get("priorityClass", ""),
            fair_share_weight=float(d.get("fairShareWeight", 1.0)),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ExperimentSpec":
        return cls.from_dict(json.loads(s))


def load_experiment_document(text: str) -> "ExperimentSpec":
    """Parse an experiment document in any shape a Katib user would bring:

    - the plain spec mapping this package serializes (`to_dict` shape),
      as JSON or YAML;
    - the reference's full CRD envelope (`apiVersion: kubeflow.org/v1beta1,
      kind: Experiment, metadata: {name}, spec: {...}` — every file under
      reference examples/v1beta1/ is this shape): the envelope is
      unwrapped, with `metadata.name` carried into the spec (the CRD keeps
      the name outside `spec`).

    JSON is attempted first (every JSON doc is also YAML 1.2, but going
    through the JSON parser keeps error messages crisp for the common
    case); YAML only on JSON failure. Non-mapping documents raise
    ValueError rather than produce an empty spec.
    """
    return experiment_spec_from_mapping(parse_spec_document(text))


def parse_spec_document(text: str) -> Any:
    """Parse JSON-or-YAML text to the raw document (no spec conversion) —
    shared by `load_experiment_document` and callers that need to mutate
    the mapping before conversion (the UI's trial_template_ref)."""
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        import yaml

        try:
            return yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise ValueError(f"spec document is neither JSON nor YAML: {e}")


def unwrap_crd_envelope(doc: Dict[str, Any]) -> Dict[str, Any]:
    """If ``doc`` is the Katib CRD envelope, return its ``spec`` mapping
    (copied) with ``metadata.name`` carried in; otherwise return ``doc``
    unchanged. The single home of the envelope predicate."""
    if doc.get("kind") == "Experiment" and isinstance(doc.get("spec"), dict):
        name = (doc.get("metadata") or {}).get("name", "")
        doc = dict(doc["spec"])
        doc.setdefault("name", name)
    return doc


def experiment_spec_from_mapping(doc: Any) -> "ExperimentSpec":
    """`load_experiment_document` for an already-parsed document: unwraps
    the CRD envelope when present, otherwise treats the mapping as the
    plain spec shape."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"spec document must be a mapping, got {type(doc).__name__}"
        )
    return ExperimentSpec.from_dict(unwrap_crd_envelope(doc))


# ---------------------------------------------------------------------------
# Assignments / observations
# ---------------------------------------------------------------------------

@dataclass
class ParameterAssignment:
    """reference trials CRD / api.proto ParameterAssignment."""

    name: str
    value: str

    def to_dict(self) -> Dict[str, str]:
        return {"name": self.name, "value": self.value}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ParameterAssignment":
        return cls(name=d["name"], value=str(d["value"]))


@dataclass
class Metric:
    """reference common_types.go Observation Metric: folded min/max/latest."""

    name: str
    min: str = "unavailable"
    max: str = "unavailable"
    latest: str = "unavailable"

    def to_dict(self) -> Dict[str, str]:
        return {"name": self.name, "min": self.min, "max": self.max, "latest": self.latest}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Metric":
        return cls(
            name=d["name"],
            min=str(d.get("min", "unavailable")),
            max=str(d.get("max", "unavailable")),
            latest=str(d.get("latest", "unavailable")),
        )


@dataclass
class Observation:
    metrics: List[Metric] = field(default_factory=list)

    def metric(self, name: str) -> Optional[Metric]:
        for m in self.metrics:
            if m.name == name:
                return m
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {"metrics": [m.to_dict() for m in self.metrics]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Observation":
        return cls(metrics=[Metric.from_dict(m) for m in d.get("metrics", [])])


# Sentinel used throughout, reference consts/const.go UnavailableMetricValue.
UNAVAILABLE_METRIC_VALUE = "unavailable"


@dataclass
class TrialAssignment:
    """reference suggestion_types.go:126-141 (TrialAssignment)."""

    name: str
    parameter_assignments: List[ParameterAssignment] = field(default_factory=list)
    early_stopping_rules: List[EarlyStoppingRule] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)

    def assignments_dict(self) -> Dict[str, str]:
        return {a.name: a.value for a in self.parameter_assignments}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "parameterAssignments": [a.to_dict() for a in self.parameter_assignments],
            "earlyStoppingRules": [r.to_dict() for r in self.early_stopping_rules],
            "labels": dict(self.labels),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialAssignment":
        return cls(
            name=d["name"],
            parameter_assignments=[ParameterAssignment.from_dict(a) for a in d.get("parameterAssignments", [])],
            early_stopping_rules=[EarlyStoppingRule.from_dict(r) for r in d.get("earlyStoppingRules", [])],
            labels=dict(d.get("labels", {})),
        )
