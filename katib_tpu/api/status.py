"""Trial / Experiment / Suggestion runtime records and condition machinery.

reference:
- trial conditions: pkg/apis/controller/trials/v1beta1/trial_types.go:106-153
  (Created/Running/Succeeded/Killed/Failed/MetricsUnavailable/EarlyStopped)
- experiment conditions: pkg/apis/controller/experiments/v1beta1/experiment_types.go:96-177
  (Created/Running/Restarting/Succeeded/Failed) + reason strings in
  pkg/controller.v1beta1/experiment/util/status_util.go
- suggestion status: pkg/apis/controller/suggestions/v1beta1/suggestion_types.go:44-124
"""

from __future__ import annotations

import enum
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .spec import (
    EarlyStoppingRule,
    ExperimentSpec,
    Observation,
    ParameterAssignment,
    TrialAssignment,
)


class TrialCondition(str, enum.Enum):
    CREATED = "Created"
    PENDING = "Pending"      # queued for a device slot (TPU-native addition)
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    KILLED = "Killed"
    FAILED = "Failed"
    METRICS_UNAVAILABLE = "MetricsUnavailable"
    EARLY_STOPPED = "EarlyStopped"


# Terminal conditions, mirroring trial util.go IsCompleted-style helpers.
TRIAL_TERMINAL = {
    TrialCondition.SUCCEEDED,
    TrialCondition.KILLED,
    TrialCondition.FAILED,
    TrialCondition.METRICS_UNAVAILABLE,
    TrialCondition.EARLY_STOPPED,
}


class ExperimentCondition(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class ExperimentReason(str, enum.Enum):
    """Terminal reasons, reference status_util.go:187-235."""

    NONE = ""
    GOAL_REACHED = "ExperimentGoalReached"
    MAX_TRIALS_REACHED = "ExperimentMaxTrialsReached"
    MAX_FAILED_TRIALS_REACHED = "ExperimentMaxFailedTrialsReached"
    SUGGESTION_END_REACHED = "ExperimentSuggestionEndReached"
    SUGGESTION_FAILED = "ExperimentSuggestionFailed"
    EXPERIMENT_FAILED = "ExperimentFailed"


@dataclass
class Condition:
    """One entry in a condition history list (type/status/reason/message/times)."""

    type: str
    status: bool = True
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastTransitionTime": self.last_transition_time,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Condition":
        return cls(
            type=d["type"],
            status=bool(d.get("status", True)),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_transition_time=float(d.get("lastTransitionTime", 0.0)),
        )


def _update_conditions(conditions: List[Condition], new: Condition) -> None:
    """Append-or-replace semantics like the reference's setCondition helpers:
    the newest condition of a type wins; older different-type conditions get
    status=False."""
    for c in conditions:
        if c.type == new.type:
            c.status = new.status
            c.reason = new.reason
            c.message = new.message
            c.last_transition_time = new.last_transition_time
            break
    else:
        conditions.append(new)
    for c in conditions:
        if c.type != new.type:
            c.status = False


@dataclass
class Trial:
    """A single evaluation — merges the reference's Trial CRD spec+status.

    reference trial_types.go:27-104.
    """

    name: str
    experiment_name: str
    parameter_assignments: List[ParameterAssignment] = field(default_factory=list)
    early_stopping_rules: List[EarlyStoppingRule] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=lambda: uuid.uuid4().hex[:12])

    # status
    condition: TrialCondition = TrialCondition.CREATED
    conditions: List[Condition] = field(default_factory=list)
    observation: Optional[Observation] = None
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    message: str = ""

    def assignments_dict(self) -> Dict[str, str]:
        return {a.name: a.value for a in self.parameter_assignments}

    @property
    def is_terminal(self) -> bool:
        return self.condition in TRIAL_TERMINAL

    @property
    def is_succeeded(self) -> bool:
        return self.condition == TrialCondition.SUCCEEDED

    @property
    def is_early_stopped(self) -> bool:
        return self.condition == TrialCondition.EARLY_STOPPED

    @property
    def current_reason(self) -> str:
        """Reason of the CURRENT condition. Not ``conditions[-1]`` — the
        _update_conditions append-or-replace semantics update a recurring
        type (e.g. Pending after a restart requeue) in place, so the last
        list entry can be a stale different-type condition."""
        for c in self.conditions:
            if c.type == self.condition.value:
                return c.reason
        return ""

    def set_condition(self, cond: TrialCondition, reason: str = "", message: str = "") -> None:
        self.condition = cond
        _update_conditions(self.conditions, Condition(type=cond.value, reason=reason, message=message))
        if cond == TrialCondition.RUNNING and self.start_time is None:
            self.start_time = time.time()
        if cond in TRIAL_TERMINAL and self.completion_time is None:
            self.completion_time = time.time()
        if message:
            self.message = message

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "experimentName": self.experiment_name,
            "uid": self.uid,
            "parameterAssignments": [a.to_dict() for a in self.parameter_assignments],
            "earlyStoppingRules": [r.to_dict() for r in self.early_stopping_rules],
            "labels": dict(self.labels),
            "condition": self.condition.value,
            "conditions": [c.to_dict() for c in self.conditions],
            "observation": self.observation.to_dict() if self.observation else None,
            "startTime": self.start_time,
            "completionTime": self.completion_time,
            "message": self.message,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Trial":
        t = cls(
            name=d["name"],
            experiment_name=d.get("experimentName", ""),
            parameter_assignments=[ParameterAssignment.from_dict(a) for a in d.get("parameterAssignments", [])],
            early_stopping_rules=[EarlyStoppingRule.from_dict(r) for r in d.get("earlyStoppingRules", [])],
            labels=dict(d.get("labels", {})),
            uid=d.get("uid", uuid.uuid4().hex[:12]),
        )
        t.condition = TrialCondition(d.get("condition", "Created"))
        t.conditions = [Condition.from_dict(c) for c in d.get("conditions", [])]
        t.observation = Observation.from_dict(d["observation"]) if d.get("observation") else None
        t.start_time = d.get("startTime")
        t.completion_time = d.get("completionTime")
        t.message = d.get("message", "")
        return t

    @classmethod
    def from_assignment(cls, assignment: TrialAssignment, experiment_name: str) -> "Trial":
        return cls(
            name=assignment.name,
            experiment_name=experiment_name,
            parameter_assignments=list(assignment.parameter_assignments),
            early_stopping_rules=list(assignment.early_stopping_rules),
            labels=dict(assignment.labels),
        )


@dataclass
class OptimalTrial:
    """reference experiment_types.go:231-245 (OptimalTrial)."""

    best_trial_name: str = ""
    parameter_assignments: List[ParameterAssignment] = field(default_factory=list)
    observation: Observation = field(default_factory=Observation)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "bestTrialName": self.best_trial_name,
            "parameterAssignments": [a.to_dict() for a in self.parameter_assignments],
            "observation": self.observation.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OptimalTrial":
        return cls(
            best_trial_name=d.get("bestTrialName", ""),
            parameter_assignments=[ParameterAssignment.from_dict(a) for a in d.get("parameterAssignments", [])],
            observation=Observation.from_dict(d.get("observation", {"metrics": []})),
        )


@dataclass
class ExperimentStatus:
    """reference experiment_types.go:79-177 (ExperimentStatus) with the 7-bucket
    trial summary from status_util.go:56-151."""

    condition: ExperimentCondition = ExperimentCondition.CREATED
    conditions: List[Condition] = field(default_factory=list)
    reason: ExperimentReason = ExperimentReason.NONE
    message: str = ""
    start_time: Optional[float] = None
    completion_time: Optional[float] = None

    trials: int = 0
    trials_succeeded: int = 0
    trials_failed: int = 0
    trials_killed: int = 0
    trials_pending: int = 0
    trials_running: int = 0
    trials_early_stopped: int = 0
    trials_metrics_unavailable: int = 0

    trial_names: List[str] = field(default_factory=list)
    succeeded_trial_names: List[str] = field(default_factory=list)
    failed_trial_names: List[str] = field(default_factory=list)
    killed_trial_names: List[str] = field(default_factory=list)
    pending_trial_names: List[str] = field(default_factory=list)
    running_trial_names: List[str] = field(default_factory=list)
    early_stopped_trial_names: List[str] = field(default_factory=list)
    metrics_unavailable_trial_names: List[str] = field(default_factory=list)

    current_optimal_trial: OptimalTrial = field(default_factory=OptimalTrial)

    @property
    def is_completed(self) -> bool:
        return self.condition in (ExperimentCondition.SUCCEEDED, ExperimentCondition.FAILED)

    @property
    def is_succeeded(self) -> bool:
        return self.condition == ExperimentCondition.SUCCEEDED

    def set_condition(
        self,
        cond: ExperimentCondition,
        reason: ExperimentReason = ExperimentReason.NONE,
        message: str = "",
    ) -> None:
        self.condition = cond
        self.reason = reason
        self.message = message
        _update_conditions(
            self.conditions, Condition(type=cond.value, reason=reason.value, message=message)
        )
        if cond == ExperimentCondition.RUNNING and self.start_time is None:
            self.start_time = time.time()
        if self.is_completed and self.completion_time is None:
            self.completion_time = time.time()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "condition": self.condition.value,
            "conditions": [c.to_dict() for c in self.conditions],
            "reason": self.reason.value,
            "message": self.message,
            "startTime": self.start_time,
            "completionTime": self.completion_time,
            "trials": self.trials,
            "trialsSucceeded": self.trials_succeeded,
            "trialsFailed": self.trials_failed,
            "trialsKilled": self.trials_killed,
            "trialsPending": self.trials_pending,
            "trialsRunning": self.trials_running,
            "trialsEarlyStopped": self.trials_early_stopped,
            "trialsMetricsUnavailable": self.trials_metrics_unavailable,
            "currentOptimalTrial": self.current_optimal_trial.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExperimentStatus":
        s = cls()
        s.condition = ExperimentCondition(d.get("condition", "Created"))
        s.conditions = [Condition.from_dict(c) for c in d.get("conditions", [])]
        s.reason = ExperimentReason(d.get("reason", ""))
        s.message = d.get("message", "")
        s.start_time = d.get("startTime")
        s.completion_time = d.get("completionTime")
        s.trials = d.get("trials", 0)
        s.trials_succeeded = d.get("trialsSucceeded", 0)
        s.trials_failed = d.get("trialsFailed", 0)
        s.trials_killed = d.get("trialsKilled", 0)
        s.trials_pending = d.get("trialsPending", 0)
        s.trials_running = d.get("trialsRunning", 0)
        s.trials_early_stopped = d.get("trialsEarlyStopped", 0)
        s.trials_metrics_unavailable = d.get("trialsMetricsUnavailable", 0)
        s.current_optimal_trial = OptimalTrial.from_dict(d.get("currentOptimalTrial", {}))
        return s


@dataclass
class Experiment:
    """Spec + status pair — the unit held by the state store."""

    spec: ExperimentSpec
    status: ExperimentStatus = field(default_factory=ExperimentStatus)

    @property
    def name(self) -> str:
        return self.spec.name

    def to_dict(self) -> Dict[str, Any]:
        return {"spec": self.spec.to_dict(), "status": self.status.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Experiment":
        return cls(
            spec=ExperimentSpec.from_dict(d["spec"]),
            status=ExperimentStatus.from_dict(d.get("status", {})),
        )


@dataclass
class SuggestionState:
    """Replaces the Suggestion CRD: demand counter vs produced assignments.

    reference suggestion_types.go:29-150 — ``spec.Requests`` is demand set by
    the experiment controller; ``status.Suggestions`` is supply appended by the
    suggestion engine; the delta is the ``current_request_number`` passed to
    the algorithm (suggestionclient.go:88-91).
    """

    experiment_name: str
    algorithm_name: str
    requests: int = 0
    suggestions: List[TrialAssignment] = field(default_factory=list)
    algorithm_settings: Dict[str, str] = field(default_factory=dict)
    failed: bool = False
    message: str = ""

    @property
    def suggestion_count(self) -> int:
        return len(self.suggestions)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "experimentName": self.experiment_name,
            "algorithmName": self.algorithm_name,
            "requests": self.requests,
            "suggestions": [s.to_dict() for s in self.suggestions],
            "algorithmSettings": dict(self.algorithm_settings),
            "failed": self.failed,
            "message": self.message,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SuggestionState":
        return cls(
            experiment_name=d["experimentName"],
            algorithm_name=d.get("algorithmName", ""),
            requests=int(d.get("requests", 0)),
            suggestions=[TrialAssignment.from_dict(s) for s in d.get("suggestions", [])],
            algorithm_settings=dict(d.get("algorithmSettings", {})),
            failed=bool(d.get("failed", False)),
            message=d.get("message", ""),
        )
