"""Deterministic fault injection for the supervised device plane (ISSUE 12).

The chaos harness is how the device plane's failure paths stay tested
without real hardware dying on cue: wedged backend probes, mid-sweep device
revocation, and process kills are *scheduled* ahead of time — keyed by the
plane's monotonic lease-grant counter and per-lease heartbeat counts, never
by wall clock or randomness at decision time — so a chaos run is exactly
reproducible.

Activation is gated behind ``KATIB_TPU_CHAOS`` (a directive string) or a
programmatic :func:`install`; when neither is set every hook below is one
``is None`` check. Directive grammar (``;`` or ``,`` separated)::

    KATIB_TPU_CHAOS="seed=7;wedge_probe=2;revoke=3@2;revoke=5;kill=4@1"

- ``seed=N``        — deterministic device choice within a revoked lease
- ``wedge_probe=N`` — the first N backend probe attempts wedge (hang past
                      the bounded timeout, surfacing the cached-verdict
                      path exactly like a dead tunnel)
- ``revoke=G[@H]``  — the G-th lease granted by the plane loses one device
                      after its H-th heartbeat (default H=1)
- ``kill=G[@H]``    — the G-th lease's holder is hard-killed after its
                      H-th heartbeat (process-death injection; the holder
                      requeues through the normal loss machinery)
- ``kill_controller=N`` — the controller SIGKILLs ITSELF right after its
                      recovery journal's N-th append of this process
                      (controller/recovery.py) — the hard-crash injection
                      the controller-kill chaos harness drives. Counter-
                      keyed like the lease-grant directives: deterministic
                      per controller incarnation, never wall-clock. Only
                      ever set on a subprocess controller (a harness
                      driver, ``bench.py controller_kill_recovery``) —
                      in-process it would kill the test runner.

The same plan object doubles as the standing bench's fault-injection knob:
``bench.py device_chaos_recovery`` installs one programmatically and
asserts zero lost observations across the injected faults.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

ENV_CHAOS = "KATIB_TPU_CHAOS"

# lease-grant actions the plane executes on the scheduled heartbeat
ACTION_REVOKE = "revoke"
ACTION_KILL = "kill"


@dataclass
class ChaosPlan:
    """One deterministic fault schedule. Counters live here (not in the
    plane) so a plan is single-use: re-running a scenario installs a fresh
    plan and replays the identical schedule."""

    seed: int = 0
    wedge_probes: int = 0
    # 1-based lease-grant index -> (action, heartbeat count before it fires)
    grant_actions: Dict[int, Tuple[str, int]] = field(default_factory=dict)
    # 1-based journal-append index at which the controller SIGKILLs itself
    # (0 = off); one-shot, keyed by the RecoveryJournal's per-process counter
    kill_controller: int = 0

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self._grants = 0
        self._wedges_left = int(self.wedge_probes)
        self._controller_killed = False

    # -- probe wedging -------------------------------------------------------

    def take_probe_wedge(self) -> bool:
        """True exactly ``wedge_probes`` times: the caller must treat this
        probe attempt as wedged (hung past its bounded timeout)."""
        with self._lock:
            if self._wedges_left > 0:
                self._wedges_left -= 1
                return True
            return False

    # -- lease-grant scheduling ----------------------------------------------

    def next_grant(self) -> Optional[Tuple[str, int, int]]:
        """Advance the grant counter; returns (action, heartbeats, pick)
        when this grant is scheduled for a fault, else None. ``pick`` is
        the deterministic index of the device to revoke within the lease
        (modulo its size, applied by the plane)."""
        with self._lock:
            self._grants += 1
            scheduled = self.grant_actions.get(self._grants)
            if scheduled is None:
                return None
            action, beats = scheduled
            return action, max(int(beats), 1), (self.seed + self._grants)

    @property
    def grants_seen(self) -> int:
        with self._lock:
            return self._grants

    # -- controller-kill scheduling ------------------------------------------

    def take_controller_kill(self, appended: int) -> bool:
        """True exactly once, at (or past — a plan installed mid-flight
        still fires) the scheduled journal append. The caller SIGKILLs the
        process, so "once" only matters for plans consulted in-process by
        tests."""
        with self._lock:
            if self.kill_controller <= 0 or self._controller_killed:
                return False
            if appended < self.kill_controller:
                return False
            self._controller_killed = True
            return True


class ChaosParseError(ValueError):
    pass


def parse_plan(directives: str) -> ChaosPlan:
    """Parse the ``KATIB_TPU_CHAOS`` directive grammar. Unknown or
    malformed directives raise — a typo'd chaos schedule silently doing
    nothing would defeat the test that relies on it."""
    plan = ChaosPlan()
    for raw in directives.replace(",", ";").split(";"):
        item = raw.strip()
        if not item:
            continue
        key, sep, value = item.partition("=")
        key = key.strip().lower()
        if not sep:
            raise ChaosParseError(f"chaos directive {item!r} is not key=value")
        value = value.strip()
        try:
            if key == "seed":
                plan.seed = int(value)
            elif key == "wedge_probe":
                plan.wedge_probes = int(value)
                plan._wedges_left = plan.wedge_probes
            elif key in (ACTION_REVOKE, ACTION_KILL):
                grant, _, beats = value.partition("@")
                plan.grant_actions[int(grant)] = (key, int(beats or "1"))
            elif key == "kill_controller":
                plan.kill_controller = int(value)
            else:
                raise ChaosParseError(f"unknown chaos directive {key!r}")
        except ValueError as e:
            if isinstance(e, ChaosParseError):
                raise
            raise ChaosParseError(f"malformed chaos directive {item!r}: {e}")
    return plan


# -- process-wide installation ------------------------------------------------

_state_lock = threading.Lock()
_PLAN: Optional[ChaosPlan] = None
_ENV_LOADED = False


def install(plan: Optional[ChaosPlan]) -> None:
    """Install (or clear, with None) the active plan programmatically —
    the bench/test entry point; wins over the environment."""
    global _PLAN, _ENV_LOADED
    with _state_lock:
        _PLAN = plan
        _ENV_LOADED = True  # explicit install pins the decision


def reset() -> None:
    """Test hook: forget the installed plan AND the env parse, so the next
    active() re-reads ``KATIB_TPU_CHAOS``."""
    global _PLAN, _ENV_LOADED
    with _state_lock:
        _PLAN = None
        _ENV_LOADED = False


def active() -> Optional[ChaosPlan]:
    """The installed plan, lazily parsed from ``KATIB_TPU_CHAOS`` on first
    consult. None (the overwhelmingly common case) costs one lock-free-ish
    check per call site."""
    global _PLAN, _ENV_LOADED
    with _state_lock:
        if _ENV_LOADED:
            return _PLAN
        _ENV_LOADED = True
        raw = os.environ.get(ENV_CHAOS, "").strip()
        if raw and raw not in ("0", "false", "off"):
            _PLAN = parse_plan(raw)
        return _PLAN
