"""Dataset loading for trial workloads.

The reference trial images download CIFAR-10/MNIST via torchvision/Keras at
container start. This environment has no network egress, so loaders look for
an on-disk copy first and otherwise generate a *learnable* synthetic
stand-in (class-conditional frequency patterns + noise) with identical
shapes/dtypes — search dynamics and benchmarks exercise the same compute
graph either way.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

CIFAR10_ENV = "KATIB_TPU_CIFAR10"  # path to an .npz with x_train/y_train/x_test/y_test


def _synthetic_images(
    n: int,
    num_classes: int,
    image_size: int,
    channels: int,
    rng: np.random.Generator,
    noise: float = 0.4,
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional 2-D sinusoid patterns; linearly separable enough to
    learn, noisy enough that accuracy tracks model capacity."""
    ys = rng.integers(0, num_classes, size=n)
    yy, xx = np.meshgrid(np.arange(image_size), np.arange(image_size), indexing="ij")
    base = np.zeros((num_classes, image_size, image_size, channels), dtype=np.float32)
    for c in range(num_classes):
        fx, fy = 1 + c % 4, 1 + (c // 4) % 4
        phase = c * 0.7
        pattern = np.sin(2 * np.pi * (fx * xx + fy * yy) / image_size + phase)
        for ch in range(channels):
            base[c, :, :, ch] = pattern * (0.5 + 0.5 * ((c + ch) % 2))
    xs = base[ys] + noise * rng.standard_normal((n, image_size, image_size, channels)).astype(
        np.float32
    )
    return xs.astype(np.float32), ys.astype(np.int32)


def load_cifar10(
    split: str = "train",
    n: Optional[int] = None,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 (NHWC float32 in [-1,1]-ish, int32 labels). Falls back to a
    synthetic 32x32x3/10-class dataset when no local copy exists."""
    path = os.environ.get(CIFAR10_ENV)
    if path and os.path.exists(path):
        data = np.load(path)
        x = data[f"x_{split}"].astype(np.float32)
        y = data[f"y_{split}"].astype(np.int32).reshape(-1)
        if x.ndim == 4 and x.shape[1] == 3:  # NCHW -> NHWC
            x = x.transpose(0, 2, 3, 1)
        if x.max() > 2.0:
            x = (x / 127.5) - 1.0
        if n is not None:
            x, y = x[:n], y[:n]
        return x, y
    rng = np.random.default_rng(seed if split == "train" else seed + 1)
    count = n if n is not None else (50000 if split == "train" else 10000)
    return _synthetic_images(count, 10, 32, 3, rng)


def load_mnist(
    split: str = "train", n: Optional[int] = None, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped dataset (28x28x1, 10 classes), synthetic fallback."""
    rng = np.random.default_rng(seed if split == "train" else seed + 1)
    count = n if n is not None else (60000 if split == "train" else 10000)
    return _synthetic_images(count, 10, 28, 1, rng)


def batches(x: np.ndarray, y: np.ndarray, batch_size: int, rng: np.random.Generator):
    """Shuffled full-epoch batch iterator (drops the ragged tail so shapes
    stay static for jit)."""
    idx = rng.permutation(len(x))
    n_batches = len(x) // batch_size
    for i in range(n_batches):
        sel = idx[i * batch_size : (i + 1) * batch_size]
        yield x[sel], y[sel]
