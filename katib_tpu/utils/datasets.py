"""Dataset loading for trial workloads.

The reference trial images download CIFAR-10/MNIST via torchvision/Keras at
container start. This environment has no network egress, so loaders look for
an on-disk copy first and otherwise generate a synthetic stand-in with
identical shapes/dtypes — search dynamics and benchmarks exercise the same
compute graph either way.

The stand-in is deliberately calibrated to *discriminate* (round-4 review:
the earlier single-template-per-class task saturated at val_acc 1.0 for half
of the benchmark's 50 trials, so optimal-trial selection and the suggesters'
rankings were exercised on a degenerate objective). Difficulty comes from
four compounding sources so accuracy tracks model capacity and optimizer
hyperparameters instead of pegging at the ceiling:

- intra-class variation: each class is a bank of prototype patterns and each
  sample a random convex mixture of them, so memorizing one template fails;
- class overlap: consecutive classes share their low-frequency component and
  differ only in the second, finer component;
- nuisance transforms: per-sample random translation (cyclic shift) and
  amplitude jitter, rewarding architectures with spatial pooling;
- distractors + noise: a low-amplitude pattern from a *different* class is
  overlaid and Gaussian pixel noise added.

At the TPU benchmark budget (192 search steps/trial: 6 epochs x 4096
examples, 8-channel supernet — scripts/run_north_star.py and bench.py's
e2e rung use exactly this) accuracy spans roughly chance to ~0.9 across an
HPO sweep; measured anchors: a 12/24-channel Adam CNN reaches ~0.9 in 96
steps at lr 3e-3 vs ~0.35 at lr 1e-4, and a 4-channel supernet at 192
steps reaches 0.44 (tests/test_datasets.py pins the contract).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

CIFAR10_ENV = "KATIB_TPU_CIFAR10"  # path to an .npz with x_train/y_train/x_test/y_test

# Difficulty calibration (see module docstring). Env-overridable so record
# captures can note the exact knobs in provenance. Read ONCE at import —
# set KATIB_TPU_SYNTH_* before importing katib_tpu, not after (a later
# setenv is a silent no-op).
#
# Label noise defaults OFF: every trial workload (darts_trainer, enas_child,
# darts_derived) carves its validation split out of load_*("train"), so
# train-split noise would corrupt the very labels trials are scored on and
# silently cap the reported ceiling. The knob exists for experiments that
# bring their own clean eval split.
SYNTH_NOISE = float(os.environ.get("KATIB_TPU_SYNTH_NOISE", "0.45"))
SYNTH_DISTRACTOR = float(os.environ.get("KATIB_TPU_SYNTH_DISTRACTOR", "0.3"))
SYNTH_VARIANTS = int(os.environ.get("KATIB_TPU_SYNTH_VARIANTS", "4"))
SYNTH_TRAIN_LABEL_NOISE = float(os.environ.get("KATIB_TPU_SYNTH_LABEL_NOISE", "0.0"))


def _prototype_bank(
    num_classes: int, image_size: int, channels: int, variants: int
) -> np.ndarray:
    """[num_classes, variants, S, S, C] bank of class patterns.

    Class c and c+1 share the coarse component (fx, fy); the variant-specific
    fine component carries the class identity, so coarse features alone
    cannot separate neighbours."""
    yy, xx = np.meshgrid(np.arange(image_size), np.arange(image_size), indexing="ij")
    proto_rng = np.random.default_rng(1234)  # bank is fixed; samples vary
    bank = np.zeros(
        (num_classes, variants, image_size, image_size, channels), dtype=np.float32
    )
    for c in range(num_classes):
        shared = c // 2  # consecutive class pairs share the coarse component
        fx, fy = 1 + shared % 3, 1 + (shared // 3) % 3
        coarse = np.sin(2 * np.pi * (fx * xx + fy * yy) / image_size + shared * 0.9)
        for v in range(variants):
            gx = int(proto_rng.integers(3, 7))
            gy = int(proto_rng.integers(3, 7))
            psi = float(proto_rng.uniform(0, 2 * np.pi)) + c * 2.1
            fine = np.sin(2 * np.pi * (gx * xx + gy * yy) / image_size + psi)
            for ch in range(channels):
                chan_gain = 0.6 + 0.4 * ((c + ch) % 2)
                bank[c, v, :, :, ch] = (0.5 * coarse + 1.0 * fine) * chan_gain
    return bank


def _synthetic_images(
    n: int,
    num_classes: int,
    image_size: int,
    channels: int,
    rng: np.random.Generator,
    noise: float = SYNTH_NOISE,
    label_noise: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Capacity-discriminative synthetic image classification task."""
    variants = max(1, SYNTH_VARIANTS)
    bank = _prototype_bank(num_classes, image_size, channels, variants)
    ys = rng.integers(0, num_classes, size=n)

    # random convex mixture over the class's variants (intra-class variation)
    w = rng.dirichlet(np.ones(variants) * 0.7, size=n).astype(np.float32)
    xs = np.einsum("nv,nvhwc->nhwc", w, bank[ys])

    # distractor overlay from a different class, random variant
    offs = rng.integers(1, num_classes, size=n)
    yd = (ys + offs) % num_classes
    vd = rng.integers(0, variants, size=n)
    xs = xs + SYNTH_DISTRACTOR * bank[yd, vd]

    # nuisance transforms: per-sample cyclic translation (bounded to a
    # quarter of the frame, so partial rather than total phase invariance
    # is required) + amplitude jitter
    max_shift = max(1, image_size // 4)
    sh = rng.integers(-max_shift, max_shift + 1, size=(n, 2))
    rows = (np.arange(image_size)[None, :] + sh[:, 0:1]) % image_size  # [n, S]
    cols = (np.arange(image_size)[None, :] + sh[:, 1:2]) % image_size
    xs = xs[np.arange(n)[:, None, None], rows[:, :, None], cols[:, None, :], :]
    xs = xs * rng.uniform(0.7, 1.3, size=(n, 1, 1, 1)).astype(np.float32)

    xs = xs + noise * rng.standard_normal(xs.shape).astype(np.float32)

    if label_noise > 0:
        flip = rng.random(n) < label_noise
        ys = np.where(flip, rng.integers(0, num_classes, size=n), ys)
    return xs.astype(np.float32), ys.astype(np.int32)


def load_cifar10(
    split: str = "train",
    n: Optional[int] = None,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 (NHWC float32 in [-1,1]-ish, int32 labels). Falls back to a
    synthetic 32x32x3/10-class dataset when no local copy exists."""
    path = os.environ.get(CIFAR10_ENV)
    if path and os.path.exists(path):
        data = np.load(path)
        x = data[f"x_{split}"].astype(np.float32)
        y = data[f"y_{split}"].astype(np.int32).reshape(-1)
        if x.ndim == 4 and x.shape[1] == 3:  # NCHW -> NHWC
            x = x.transpose(0, 2, 3, 1)
        if x.max() > 2.0:
            x = (x / 127.5) - 1.0
        if n is not None:
            x, y = x[:n], y[:n]
        return x, y
    rng = np.random.default_rng(seed if split == "train" else seed + 1)
    count = n if n is not None else (50000 if split == "train" else 10000)
    return _synthetic_images(
        count, 10, 32, 3, rng,
        label_noise=SYNTH_TRAIN_LABEL_NOISE if split == "train" else 0.0,
    )


def load_mnist(
    split: str = "train", n: Optional[int] = None, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped dataset (28x28x1, 10 classes), synthetic fallback."""
    rng = np.random.default_rng(seed if split == "train" else seed + 1)
    count = n if n is not None else (60000 if split == "train" else 10000)
    return _synthetic_images(
        count, 10, 28, 1, rng,
        label_noise=SYNTH_TRAIN_LABEL_NOISE if split == "train" else 0.0,
    )


DIGITS_PROVENANCE = (
    "real UCI handwritten digits (sklearn.datasets.load_digits: 1797 8x8 "
    "grayscale images, 10 classes) — genuine real-world data bundled with "
    "scikit-learn, the only real image-classification dataset available "
    "in this zero-egress environment"
)


def load_digits(
    split: str = "train",
    n: Optional[int] = None,
    seed: int = 0,
    image_size: int = 8,
    channels: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """REAL image data: the UCI handwritten-digits set bundled with
    scikit-learn (1797 8x8 grayscale images, 10 classes).

    Every other loader in this module falls back to a synthetic stand-in
    because CIFAR-10/MNIST downloads are blocked by zero egress (round-4
    review, Missing #1: 'real-dataset accuracy parity' was the top evidence
    gap). This one never synthesizes: the pixels are genuine scans of
    handwritten digits, so HPO records built on it verify the real-data
    axis — small scale, honestly labeled (see DIGITS_PROVENANCE).

    Deterministic 80/20 shuffle-split (1437 train / 360 val) with a fixed
    split seed so train/val are disjoint across calls regardless of
    ``seed``, which only controls subset sampling when ``n`` is given.
    ``image_size`` (multiple of 8) nearest-neighbour-upsamples for models
    built for larger frames; ``channels`` tiles grayscale for RGB stems.
    Values are scaled from [0, 16] to [-1, 1]. ``n`` is capped at the
    split's true size — 1797 real samples is what exists.
    """
    from sklearn.datasets import load_digits as _sk_digits

    d = _sk_digits()
    x = d.images.astype(np.float32) / 8.0 - 1.0
    y = d.target.astype(np.int32)
    split_rng = np.random.default_rng(7)  # split is fixed; never reseeded
    idx = split_rng.permutation(len(x))
    x, y = x[idx], y[idx]
    n_train = (len(x) * 4) // 5
    if split == "train":
        x, y = x[:n_train], y[:n_train]
    else:
        x, y = x[n_train:], y[n_train:]
    if image_size != 8:
        if image_size % 8:
            raise ValueError(f"image_size must be a multiple of 8, got {image_size}")
        k = image_size // 8
        x = np.kron(x, np.ones((1, k, k), dtype=np.float32))
    x = x[..., None]
    if channels > 1:
        x = np.tile(x, (1, 1, 1, channels))
    if n is not None and n < len(x):
        sel = np.random.default_rng(seed).permutation(len(x))[:n]
        x, y = x[sel], y[sel]
    return np.ascontiguousarray(x), np.ascontiguousarray(y)


def load_dataset(
    name: str,
    split: str = "train",
    n: Optional[int] = None,
    image_size: int = 32,
    channels: int = 3,
) -> Tuple[np.ndarray, np.ndarray]:
    """One-line dataset dispatch for trial workloads: ``"digits"`` is the
    REAL bundled UCI scans adapted to the requested stem shape; anything
    else is the CIFAR-10 loader (real npz when present, calibrated
    synthetic stand-in otherwise). Keeps the digits adapter arguments in
    one place so every record family trains on identically shaped data.
    Unknown names raise — a typo must not silently train on the synthetic
    stand-in while the record claims real-digits provenance."""
    if name == "digits":
        return load_digits(split, n=n, image_size=image_size, channels=channels)
    if name in ("cifar", "cifar10"):
        return load_cifar10(split, n=n)
    raise ValueError(f"unknown dataset {name!r}; expected 'digits' or 'cifar'")


def batches(x: np.ndarray, y: np.ndarray, batch_size: int, rng: np.random.Generator):
    """Shuffled full-epoch batch iterator (drops the ragged tail so shapes
    stay static for jit)."""
    idx = rng.permutation(len(x))
    n_batches = len(x) // batch_size
    for i in range(n_batches):
        sel = idx[i * batch_size : (i + 1) * batch_size]
        yield x[sel], y[sel]
