"""Wall-clock measurement that is honest on tunneled TPU backends.

On a proxied accelerator (e.g. a TPU reached through a network tunnel)
``jax.block_until_ready`` can return before the remote execution has actually
finished — a 28ms train step "completes" in 0.3ms — so any loop timed that
way under-reports by orders of magnitude. A device->host read of one element
cannot lie: the value isn't available until the producing computation (and,
through data dependencies, everything it chains from) has run.

The recipe used by bench.py and the hardware-gated perf tests:

1. ``host_sync`` once before starting the clock (drains queued work);
2. chain each iteration's output into the next iteration's input so the
   loop cannot be reordered or deduplicated;
3. ``host_sync`` the final output — one round-trip for the whole loop;
4. subtract ``roundtrip_ms`` (the cost of step 3) and divide by N.
"""

from __future__ import annotations

import time


def host_sync(x) -> float:
    """Force completion with a 1-element device->host read; returns it."""
    import jax.numpy as jnp

    return float(jnp.ravel(x)[0])


def roundtrip_ms(repeats: int = 3) -> float:
    """Per-call dispatch + host-read round-trip latency in milliseconds
    (low single-digit ms through a healthy axon tunnel, ~70-90ms when the
    tunnel is degraded, microseconds on a local device) — bench.py's probe
    uses this as its tunnel-health signal."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((8,))
    host_sync(f(x))
    t0 = time.time()
    for _ in range(repeats):
        x = f(x)
        host_sync(x)
    return (time.time() - t0) / repeats * 1e3
