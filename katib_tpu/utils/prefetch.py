"""Device-prefetching input pipeline.

TPU-idiomatic double buffering: while the accelerator runs step N, the next
batches are already being transferred. Passing raw numpy into a jitted step
makes the transfer synchronous inside the dispatch — measured at ~55ms of a
57ms DARTS search step through a tunneled TPU — whereas `jax.device_put`
returns immediately and the copy overlaps with compute. The reference
delegates input pipelines to its trial images (tf.data / torch DataLoader
workers); this is the framework-native equivalent for JAX trials.

``prefetch_to_device(it, size=2)`` wraps any iterator of (pytrees of) numpy
arrays, keeping ``size`` batches in flight on the device (or sharded with
``sharding``). All model trainers consume their epoch iterators through it.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator, Optional

import jax


def prefetch_to_device(
    iterator: Iterable[Any],
    size: int = 2,
    sharding: Optional[Any] = None,
) -> Iterator[Any]:
    """Yield items of ``iterator`` staged on device ``size`` batches ahead.

    ``sharding`` may be a Device, Sharding, or None (uncommitted placement on
    the default device — preferred on tunneled backends, where committed
    arrays dispatch slowly; see katib_tpu/utils/timing.py).
    """
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def _stage(batch):
        if sharding is None:
            return jax.tree.map(jax.numpy.asarray, batch)
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

    for batch in itertools.islice(it, size):
        queue.append(_stage(batch))
    while queue:
        yield queue.popleft()
        for batch in itertools.islice(it, 1):
            queue.append(_stage(batch))
