"""Force a process onto the CPU backend even when the TPU tunnel is wedged.

Popping ``PALLAS_AXON_POOL_IPS`` inside a running interpreter is NOT
sufficient: the axon sitecustomize has already read it at interpreter start
and dialed the tunnel, and with that connection pending a wedged tunnel
blocks JAX's plugin initialization even under ``JAX_PLATFORMS=cpu``.
Measured 2026-08-01 on a fully wedged tunnel: the in-process env dance hung
past a 1200s timeout at the first jax import, while the same workload with
the variable stripped at process start finished in 15s.

``ensure_cpu_process()`` is the one correct way for a script to force CPU:
call it BEFORE anything imports jax. If the pool variable was present at
interpreter start it re-execs the current process once with the variable
stripped (the env mutation makes the second pass fall through, so no exec
loop). Child-process spawners should instead build the child env with
``cpu_child_env()`` so the child never sees the variable at all.

This module must stay import-light (stdlib only) — it runs before JAX.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional

POOL_VAR = "PALLAS_AXON_POOL_IPS"


def ensure_cpu_process() -> None:
    """Pin this process to XLA:CPU; re-exec once if the axon pool variable
    was present at interpreter start (see module docstring). Call before
    any jax import; after it returns, ``import jax`` is wedge-proof."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if POOL_VAR in os.environ:
        env = {k: v for k, v in os.environ.items() if k != POOL_VAR}
        os.execve(sys.executable, [sys.executable] + sys.argv, env)


def cpu_child_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for spawning a CPU-pinned child process: the axon pool
    variable stripped (so its sitecustomize never dials the tunnel) and
    ``JAX_PLATFORMS=cpu`` set."""
    env = dict(os.environ if base is None else base)
    env.pop(POOL_VAR, None)
    env["JAX_PLATFORMS"] = "cpu"
    return env
