"""Bounded accelerator-backend probing (ISSUE 8 satellite).

Two bench rounds were lost to TPU backend init/probe failures
(BENCH_r01–r05): the first ``jax.local_devices()`` of a process initializes
the backend, and on a wedged tunneled runtime that call can block for
minutes — inside the telemetry sampler tick, the admission pre-flight, or a
compile worker. This module wraps the first probe in a
retry-with-timeout helper that runs the init on a disposable daemon thread:
a wedge costs the caller at most ``timeout_seconds`` per attempt, the
failure is surfaced once as a ``BackendInitFailed`` warning event, and the
process-wide verdict is cached so subsequent calls are either a direct
(already-initialized, fast) call or an immediate None — never a second
wedge.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, List, Optional

log = logging.getLogger("katib_tpu.backend")

_state_lock = threading.Lock()
_BACKEND_OK: Optional[bool] = None  # None = not yet probed this process
_EVENT_EMITTED = False


def reset_probe_state() -> None:
    """Test hook: forget the cached verdict + event dedup."""
    global _BACKEND_OK, _EVENT_EMITTED
    with _state_lock:
        _BACKEND_OK = None
        _EVENT_EMITTED = False


def _emit_failed(events, reason: str) -> None:
    global _EVENT_EMITTED
    with _state_lock:
        if _EVENT_EMITTED:
            return
        _EVENT_EMITTED = True
    log.warning("accelerator backend init/probe failed: %s", reason)
    if events is not None:
        try:
            events.event(
                "", "Controller", "backend", "BackendInitFailed",
                f"accelerator backend init/probe failed ({reason}); "
                "device telemetry/capacity detection disabled for this "
                "process — trials still run, but check the tunnel/runtime",
                warning=True,
            )
        except Exception:
            pass


def bounded_local_devices(
    timeout_seconds: float = 15.0,
    retries: int = 2,
    backoff_seconds: float = 1.0,
    events=None,
) -> Optional[List[Any]]:
    """``jax.local_devices()`` with a bounded first init.

    Returns the device list, or None when the backend cannot be probed —
    after ``retries`` attempts of at most ``timeout_seconds`` each, a
    ``BackendInitFailed`` warning event is emitted (once per process) and
    every later call returns None immediately. Once a probe succeeds, later
    calls go straight to ``jax.local_devices()`` (the backend is
    initialized; the call is cheap)."""
    global _BACKEND_OK
    with _state_lock:
        verdict = _BACKEND_OK
    if verdict is False:
        return None
    if verdict is True:
        import jax

        try:
            return jax.local_devices()
        except Exception:
            return None  # initialized backend lost mid-process; don't re-wedge

    from . import chaos

    last_error = "?"
    for attempt in range(max(int(retries), 1)):
        plan = chaos.active()
        if plan is not None and plan.take_probe_wedge():
            # injected wedge (utils/chaos.py): behave exactly like a probe
            # that hung past its bounded timeout — same error string, same
            # cached-verdict consequences — without burning the wall clock
            last_error = (
                f"probe hung past {timeout_seconds:.0f}s "
                f"(attempt {attempt + 1}; chaos-injected wedge)"
            )
            continue
        box: dict = {}

        def _probe():
            try:
                import jax

                box["devices"] = jax.local_devices()
            except BaseException as e:  # noqa: BLE001 — surfaced as the reason
                box["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=_probe, daemon=True, name="backend-probe")
        t.start()
        t.join(timeout_seconds)
        if t.is_alive():
            last_error = f"probe hung past {timeout_seconds:.0f}s (attempt {attempt + 1})"
        elif "error" in box:
            last_error = box["error"]
        else:
            with _state_lock:
                _BACKEND_OK = True
            return box["devices"]
        if attempt + 1 < max(int(retries), 1):
            time.sleep(backoff_seconds)
    with _state_lock:
        _BACKEND_OK = False
    _emit_failed(events, last_error)
    return None


def bounded_devices(
    timeout_seconds: float = 15.0,
    retries: int = 2,
    events=None,
) -> Optional[List[Any]]:
    """``jax.devices()`` (the global view) behind the same bounded first
    init and cached verdict as :func:`bounded_local_devices`.

    This is the one sanctioned route to the global device list — the
    analyzer's KTI304 rule flags direct ``jax.devices()`` /
    ``jax.local_devices()`` calls outside this module, because every
    unguarded call site re-opens the BENCH_r01–r05 wedge class (the first
    probe of a process can hang for minutes on a dead tunnel). Returns None
    when the backend cannot be probed."""
    if bounded_local_devices(timeout_seconds, retries, events=events) is None:
        return None
    import jax

    try:
        return jax.devices()
    except Exception:
        return None  # backend lost between the probe and this call


def require_devices(
    timeout_seconds: float = 15.0,
    retries: int = 2,
    events=None,
) -> List[Any]:
    """:func:`bounded_devices` that raises instead of returning None — for
    call sites (mesh construction, worker bootstrap) that cannot proceed
    without a backend. The raise is loud and immediate; the legacy direct
    call would have hung the caller on a wedged tunnel instead."""
    devices = bounded_devices(timeout_seconds, retries, events=events)
    if not devices:
        raise RuntimeError(
            "accelerator backend unavailable: bounded probe failed or wedged "
            "(see the BackendInitFailed event for the first failure's reason)"
        )
    return devices


def probe_verdict() -> Optional[bool]:
    """The cached process-wide backend verdict: True (healthy), False
    (wedged/dead — every probe call short-circuits to None), or None (not
    yet probed). Read-only view for the device plane's health snapshot."""
    with _state_lock:
        return _BACKEND_OK
