"""End-to-end experiment result verification — the reference's e2e checker
re-expressed against katib-tpu types.

reference test/e2e/v1beta1/scripts/gh-actions/run-e2e-experiment.py:17-120:
- the optimal trial must carry the objective metric;
- Succeeded(MaxTrialsReached) => succeeded + early-stopped == maxTrialCount;
- Succeeded(GoalReached) => the best metric actually satisfies the goal;
- suggestion lifecycle honors the resume policy: LongRunning keeps the
  algorithm instance alive for budget-raise restarts, Never/FromVolume tear
  it down (the reference deletes the suggestion Deployment/Service; here the
  in-memory suggester is dropped, FromVolume keeping its on-disk state).

Used by tests AND by the bench harness's e2e stage, so the driver's bench
run doubles as an invariant check on real hardware.
"""

from __future__ import annotations

from typing import List

from ..api.spec import ObjectiveType, ResumePolicy
from ..api.status import Experiment, ExperimentReason


class E2EVerificationError(AssertionError):
    pass


def verify_experiment_results(ctrl, exp: Experiment) -> None:
    """Raise E2EVerificationError on any violated invariant."""
    errs: List[str] = []
    spec = exp.spec
    status = exp.status

    if not status.is_completed:
        errs.append(f"experiment not completed: {status.condition}")

    # 1. optimal trial must exist and carry the objective metric
    optimal = status.current_optimal_trial
    best_metric = None
    if optimal is None or optimal.observation is None:
        errs.append("no current_optimal_trial with an observation")
    else:
        best_metric = optimal.observation.metric(spec.objective.objective_metric_name)
        if best_metric is None:
            errs.append(
                f"optimal trial lacks objective metric "
                f"{spec.objective.objective_metric_name!r}"
            )

    # 2. MaxTrialsReached => all budgeted trials completed
    if status.reason == ExperimentReason.MAX_TRIALS_REACHED:
        completed = status.trials_succeeded + status.trials_early_stopped
        if spec.max_trial_count is not None and completed != spec.max_trial_count:
            errs.append(
                f"MaxTrialsReached but completed {completed} != "
                f"maxTrialCount {spec.max_trial_count}"
            )

    # 3. GoalReached => the metric must actually satisfy the goal
    if (
        status.reason == ExperimentReason.GOAL_REACHED
        and spec.objective.goal is not None
        and best_metric is not None
    ):
        goal = float(spec.objective.goal)
        if spec.objective.type == ObjectiveType.MINIMIZE:
            if float(best_metric.min) > goal:
                errs.append(
                    f"GoalReached but best min {best_metric.min} > goal {goal}"
                )
        elif float(best_metric.max) < goal:
            errs.append(f"GoalReached but best max {best_metric.max} < goal {goal}")

    # 4. suggestion lifecycle per resume policy
    alive = ctrl.suggestions.has_suggester(exp.name)
    if spec.resume_policy == ResumePolicy.LONG_RUNNING and not alive:
        errs.append("LongRunning resume policy but suggester was torn down")
    if spec.resume_policy in (ResumePolicy.NEVER, ResumePolicy.FROM_VOLUME) and alive:
        errs.append(
            f"{spec.resume_policy.value} resume policy but suggester still alive"
        )
    if spec.resume_policy == ResumePolicy.FROM_VOLUME:
        # on-disk state must survive teardown for a later FromVolume restore
        if ctrl.state.root and ctrl.state.get_suggestion(exp.name) is None:
            errs.append("FromVolume: no persisted suggestion state after completion")

    if errs:
        raise E2EVerificationError("; ".join(errs))
