"""Model parameter initialization that is cheap on high-latency backends.

Eager ``flax`` ``Module.init`` issues one device dispatch per parameter —
measured ~80s for a small DARTS supernet through a tunneled TPU (~90ms per
round trip) vs ~9s as a single jitted computation. Every trial entry point
should initialize through this helper rather than calling ``model.init``
eagerly.
"""

from __future__ import annotations

import functools

import jax


@functools.lru_cache(maxsize=32)
def _cached_init_fn(model):
    # one jitted init per (hashable) module config: repeated trials of an
    # HPO sweep reuse the same callable and skip the init retrace
    return jax.jit(model.init)


def jitted_init(model, rngs, *args, device=None):
    """``model.init`` as one jitted computation; returns the ``params``
    collection. ``device`` (optional) places the result on a specific device
    via ``jax.default_device`` — arrays stay *uncommitted*, which matters on
    tunneled backends where committed inputs take a ~45x slower dispatch
    path (see katib_tpu.parallel.train.make_lm_train_step).
    """
    import contextlib

    try:
        fn = _cached_init_fn(model)  # flax Modules with hashable fields
    except TypeError:
        fn = jax.jit(model.init)  # unhashable config: uncached fallback
    ctx = jax.default_device(device) if device is not None else contextlib.nullcontext()
    with ctx:
        return fn(rngs, *args)["params"]
