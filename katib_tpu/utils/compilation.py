"""JAX persistent compilation cache setup.

TPU-native operational win with no reference counterpart: trial processes in
an HPO sweep compile the SAME program shapes over and over (only
hyperparameter *values* differ, and most are baked as runtime scalars, not
shapes). Pointing every trial at a shared on-disk XLA compilation cache turns
the 20-150s first-compile into a cache hit for all subsequent trials —
usually the single largest wall-clock lever for a 50-trial experiment.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache", "katib_tpu", "xla")
# Persist EVERY compile by default (jax's own default of 1.0s skips
# sub-second programs, which defeats warm-start for small CPU-bench sweeps
# — ISSUE 8 satellite). Operators raise it via the RuntimeConfig field
# `xla_cache_min_compile_seconds` / the env var below when cache-dir churn
# matters more than warm-start.
_DEFAULT_MIN_COMPILE_SECS = 0.0
ENV_MIN_COMPILE_SECS = "KATIB_TPU_XLA_CACHE_MIN_COMPILE_SECONDS"
_initialized = False


def _accelerator_platform(platforms: str, environ=None, libtpu_present=None) -> bool:
    """Whether the process will (likely) run on an accelerator, decided
    WITHOUT initializing a backend. ``platforms`` is the lowercased
    jax_platforms config/env value ("" = auto-detect). On auto-detect,
    accelerator presence is inferred from env hints / an installed libtpu —
    a CPU-only host must not get the SIGILL-prone XLA:CPU cache, and a
    wedged accelerator runtime must not be probed (jax.default_backend()
    blocks for minutes inside the first trial's worker thread)."""
    env = os.environ if environ is None else environ
    if platforms.startswith("cpu"):
        return False
    if platforms:
        return True  # tpu / axon / cuda / ... explicitly selected
    if libtpu_present is None:
        import importlib.util

        libtpu_present = importlib.util.find_spec("libtpu") is not None
    return bool(
        env.get("PALLAS_AXON_POOL_IPS") or env.get("TPU_NAME") or libtpu_present
    )


def min_compile_seconds_from_env(default: float = _DEFAULT_MIN_COMPILE_SECS) -> float:
    """The persisted-entry threshold: RuntimeConfig stamps
    ``xla_cache_min_compile_seconds`` into the environment (so trial
    subprocesses and lazy enables agree); a malformed value keeps the
    default rather than crashing at import."""
    raw = os.environ.get(ENV_MIN_COMPILE_SECS, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def enable_compilation_cache(
    directory: Optional[str] = None, min_compile_seconds: Optional[float] = None
) -> str:
    """Idempotently enable the persistent cache; returns the cache dir.

    Accelerator platforms only: XLA:CPU persists AOT results keyed loosely
    enough that entries written on a host with different CPU features load
    with a SIGILL warning — and CPU compiles are cheap anyway.

    The platform check reads config/env, NEVER ``jax.default_backend()``:
    probing the backend initializes it, and on a wedged tunneled-TPU runtime
    that can block for minutes — inside the first trial's worker thread,
    before any user code runs (observed as a trial stuck Running forever
    while its siblings completed)."""
    global _initialized
    import jax

    cache_dir = directory or os.environ.get("KATIB_TPU_XLA_CACHE", _DEFAULT_DIR)
    if _initialized:
        return cache_dir
    platforms = (
        (jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS") or "").lower()
    )
    if not _accelerator_platform(platforms):
        _initialized = True
        return cache_dir
    if min_compile_seconds is None:
        min_compile_seconds = min_compile_seconds_from_env()
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_seconds)
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _initialized = True
    return cache_dir
