"""JAX persistent compilation cache setup.

TPU-native operational win with no reference counterpart: trial processes in
an HPO sweep compile the SAME program shapes over and over (only
hyperparameter *values* differ, and most are baked as runtime scalars, not
shapes). Pointing every trial at a shared on-disk XLA compilation cache turns
the 20-150s first-compile into a cache hit for all subsequent trials —
usually the single largest wall-clock lever for a 50-trial experiment.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache", "katib_tpu", "xla")
_initialized = False


def enable_compilation_cache(directory: Optional[str] = None) -> str:
    """Idempotently enable the persistent cache; returns the cache dir.

    Accelerator backends only: XLA:CPU persists AOT results keyed loosely
    enough that entries written on a host with different CPU features load
    with a SIGILL warning — and CPU compiles are cheap anyway."""
    global _initialized
    import jax

    cache_dir = directory or os.environ.get("KATIB_TPU_XLA_CACHE", _DEFAULT_DIR)
    if _initialized:
        return cache_dir
    if jax.default_backend() == "cpu":
        _initialized = True
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _initialized = True
    return cache_dir
