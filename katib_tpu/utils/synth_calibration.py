"""TPU-rung difficulty overrides for the synthetic stand-in.

`utils/datasets.py` reads its KATIB_TPU_SYNTH_* knobs once at import. The
round-5 defaults there are calibrated for the CPU-scale records; at the TPU
benchmark rung (8-channel supernet, 192 search steps —
scripts/run_north_star.py --tpu and bench.py's TPU e2e ladder) those
defaults leave the ceiling too wide: any decent w_lr reaches ~1.0, TPE
exploits into the plateau, and the 50-trial quartiles degenerate
(examples/records/darts_hpo_50trials_tpu.json, 2026-08-01 first recapture).

This module is the single home of the harder TPU-rung knob set, chosen by
the measured sweep in scripts/calibrate_tpu_objective.py. It must stay
import-light (no heavy deps, no katib_tpu.utils.datasets import): callers
apply the overrides to os.environ BEFORE anything imports datasets.

An empty TPU_RUNG_KNOBS means "not yet calibrated" — apply() is a no-op
and the rung runs at the datasets.py defaults.
"""

from __future__ import annotations

import os
from typing import Dict, MutableMapping, Optional

# Chosen by scripts/calibrate_tpu_objective.py (good/mid/bad optimizer
# probes at the exact north-star TPU scale). Values are strings because
# they land in os.environ.
#
# Current set: candidate 1 (noise 1.0 / distractor 0.6 / variants 6).
# Provenance: the on-chip sweep measured candidate 0 (0.8/0.5/6) still
# saturating at the optimum (supernet good-probe 0.983) before the tunnel
# wedged; a CPU CNN-proxy sweep (2026-08-01, /tmp sweep recorded in the
# round-5 map) placed candidate 1 at 3x candidate 0's difficulty (CNN
# good-probe 0.596 -> 0.203) with candidates 2-3 at chance, bracketing
# the sub-saturating ceiling between 1 and 2. On-chip confirmation
# re-stamps this block when a tunnel window opens.
TPU_RUNG_KNOBS: Dict[str, str] = {
    "KATIB_TPU_SYNTH_NOISE": "1.0",
    "KATIB_TPU_SYNTH_DISTRACTOR": "0.6",
    "KATIB_TPU_SYNTH_VARIANTS": "6",
}


def apply_tpu_rung_knobs(
    env: Optional[MutableMapping[str, str]] = None,
) -> Dict[str, str]:
    """Set the TPU-rung difficulty knobs into ``env`` (default os.environ),
    set-if-unset so an operator's explicit KATIB_TPU_SYNTH_* override always
    wins. Returns the knobs actually applied. Call BEFORE importing
    katib_tpu.utils.datasets (the knobs are read there at import time)."""
    if env is None:
        env = os.environ
    applied: Dict[str, str] = {}
    for key, value in TPU_RUNG_KNOBS.items():
        if key not in env:
            env[key] = value
            applied[key] = value
    return applied
