"""Experiment state store — replaces Kubernetes CRDs/etcd as declarative state.

The reference persists Experiment/Suggestion/Trial objects as CRs in etcd and
controllers watch them. Here the orchestrator is a single process, so state is
a thread-safe registry with optional JSON persistence per experiment under
``<root>/<experiment>/state.json`` (FromVolume resume policy restores from it —
reference composer.go:121-133 PVC semantics).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..api.spec import ExperimentSpec
from ..api.status import Experiment, SuggestionState, Trial


class ExperimentStateStore:
    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root
        self._lock = threading.RLock()
        self._experiments: Dict[str, Experiment] = {}
        self._trials: Dict[str, Dict[str, Trial]] = {}
        self._suggestions: Dict[str, SuggestionState] = {}
        self._templates: Dict[str, dict] = {}
        if root:
            os.makedirs(root, exist_ok=True)
            self._load_templates()

    # -- experiments --------------------------------------------------------

    def create_experiment(self, exp: Experiment) -> Experiment:
        with self._lock:
            if exp.name in self._experiments:
                raise ValueError(f"experiment {exp.name!r} already exists")
            self._experiments[exp.name] = exp
            self._trials.setdefault(exp.name, {})
            self._persist(exp.name)
            return exp

    def get_experiment(self, name: str) -> Optional[Experiment]:
        with self._lock:
            return self._experiments.get(name)

    def list_experiments(self) -> List[Experiment]:
        with self._lock:
            return list(self._experiments.values())

    def update_experiment(self, exp: Experiment) -> None:
        with self._lock:
            self._experiments[exp.name] = exp
            self._persist(exp.name)

    def delete_experiment(self, name: str) -> None:
        with self._lock:
            self._experiments.pop(name, None)
            self._trials.pop(name, None)
            self._suggestions.pop(name, None)
            if self.root:
                p = self._path(name)
                if os.path.exists(p):
                    os.remove(p)

    # -- trials -------------------------------------------------------------

    def create_trial(self, trial: Trial) -> Trial:
        with self._lock:
            exp_trials = self._trials.setdefault(trial.experiment_name, {})
            if trial.name in exp_trials:
                raise ValueError(f"trial {trial.name!r} already exists")
            exp_trials[trial.name] = trial
            self._persist(trial.experiment_name)
            return trial

    def get_trial(self, experiment_name: str, trial_name: str) -> Optional[Trial]:
        with self._lock:
            return self._trials.get(experiment_name, {}).get(trial_name)

    def list_trials(self, experiment_name: str) -> List[Trial]:
        """Label-selector list in the reference (experiment_controller.go:263);
        returned in creation order."""
        with self._lock:
            return list(self._trials.get(experiment_name, {}).values())

    def update_trial(self, trial: Trial) -> None:
        with self._lock:
            self._trials.setdefault(trial.experiment_name, {})[trial.name] = trial
            self._persist(trial.experiment_name)

    def delete_trial(self, experiment_name: str, trial_name: str) -> None:
        with self._lock:
            self._trials.get(experiment_name, {}).pop(trial_name, None)
            self._persist(experiment_name)

    # -- suggestion state ----------------------------------------------------

    def get_suggestion(self, experiment_name: str) -> Optional[SuggestionState]:
        with self._lock:
            return self._suggestions.get(experiment_name)

    def put_suggestion(self, s: SuggestionState) -> None:
        with self._lock:
            self._suggestions[s.experiment_name] = s
            self._persist(s.experiment_name)

    def delete_suggestion(self, experiment_name: str) -> None:
        with self._lock:
            self._suggestions.pop(experiment_name, None)
            self._persist(experiment_name)

    # -- trial templates ------------------------------------------------------
    # Reference: the UI's trial-template configmap CRUD
    # (pkg/ui/v1beta1/backend.go template endpoints); here templates are
    # TrialTemplate JSON dicts persisted under <root>/templates/.

    def put_template(self, name: str, template: dict) -> None:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"invalid template name {name!r}")
        with self._lock:
            self._templates[name] = template
            if self.root:
                d = os.path.join(self.root, "templates")
                os.makedirs(d, exist_ok=True)
                tmp = os.path.join(d, name + ".json.tmp")
                with open(tmp, "w") as f:
                    json.dump(template, f, indent=2)
                os.replace(tmp, os.path.join(d, name + ".json"))

    def get_template(self, name: str) -> Optional[dict]:
        with self._lock:
            return self._templates.get(name)

    def list_templates(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._templates)

    def delete_template(self, name: str) -> None:
        with self._lock:
            self._templates.pop(name, None)
            if self.root:
                p = os.path.join(self.root, "templates", name + ".json")
                if os.path.exists(p):
                    os.remove(p)

    def _load_templates(self) -> None:
        d = os.path.join(self.root, "templates")
        if not os.path.isdir(d):
            return
        for fn in os.listdir(d):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(d, fn)) as f:
                        self._templates[fn[:-5]] = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue

    # -- persistence ---------------------------------------------------------

    def _path(self, name: str) -> str:
        assert self.root is not None
        return os.path.join(self.root, name, "state.json")

    def _persist(self, name: str) -> None:
        if not self.root:
            return
        exp = self._experiments.get(name)
        if exp is None:
            return
        payload = {
            "experiment": exp.to_dict(),
            "trials": [t.to_dict() for t in self._trials.get(name, {}).values()],
            "suggestion": self._suggestions[name].to_dict() if name in self._suggestions else None,
            "savedAt": time.time(),
        }
        p = self._path(name)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, p)

    def load(self, name: str) -> Optional[Experiment]:
        """FromVolume resume: restore experiment + trials + suggestion state."""
        if not self.root:
            return None
        p = self._path(name)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            payload = json.load(f)
        with self._lock:
            exp = Experiment.from_dict(payload["experiment"])
            self._experiments[name] = exp
            self._trials[name] = {t["name"]: Trial.from_dict(t) for t in payload.get("trials", [])}
            if payload.get("suggestion"):
                self._suggestions[name] = SuggestionState.from_dict(payload["suggestion"])
            return exp

    def experiment_dir(self, name: str) -> Optional[str]:
        if not self.root:
            return None
        d = os.path.join(self.root, name)
        os.makedirs(d, exist_ok=True)
        return d
