"""Experiment state store — replaces Kubernetes CRDs/etcd as declarative state.

The reference persists Experiment/Suggestion/Trial objects as CRs in etcd and
controllers watch them. Here the orchestrator is a single process, so state is
a thread-safe registry with optional JSON persistence per experiment
(FromVolume resume policy restores from it — reference composer.go:121-133
PVC semantics).

Layout mirrors etcd's one-object-per-key: each record persists to its own
file under ``<root>/<experiment>/state/`` (``experiment.json``,
``suggestion.json``, ``trials/<trial>.json``), so a mutation rewrites only
the changed record — a trial status flip is O(1), not O(#trials) — while
every write stays individually atomic (tmp + rename). The pre-round-4
single-file ``state.json`` snapshot is still readable for resume.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..api.spec import ExperimentSpec
from ..api.status import Experiment, SuggestionState, Trial


class ExperimentStateStore:
    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root
        self._lock = threading.RLock()
        self._experiments: Dict[str, Experiment] = {}
        self._trials: Dict[str, Dict[str, Trial]] = {}
        self._suggestions: Dict[str, SuggestionState] = {}
        self._templates: Dict[str, dict] = {}
        # creation-order bookkeeping for the per-record layout: a monotonic
        # per-experiment counter (never reused after deletes) and the seq
        # assigned to each live trial
        self._next_seq: Dict[str, int] = {}
        self._trial_seq: Dict[str, Dict[str, int]] = {}
        if root:
            os.makedirs(root, exist_ok=True)
            self._load_templates()

    # -- experiments --------------------------------------------------------

    def create_experiment(self, exp: Experiment) -> Experiment:
        with self._lock:
            if exp.name in self._experiments:
                raise ValueError(f"experiment {exp.name!r} already exists")
            self._experiments[exp.name] = exp
            self._trials.setdefault(exp.name, {})
            self._persist(exp.name)
            return exp

    def get_experiment(self, name: str) -> Optional[Experiment]:
        with self._lock:
            return self._experiments.get(name)

    def list_experiments(self) -> List[Experiment]:
        with self._lock:
            return list(self._experiments.values())

    def update_experiment(self, exp: Experiment) -> None:
        with self._lock:
            self._experiments[exp.name] = exp
            self._persist(exp.name)

    def delete_experiment(self, name: str) -> None:
        import shutil

        with self._lock:
            self._experiments.pop(name, None)
            self._trials.pop(name, None)
            self._suggestions.pop(name, None)
            self._trial_seq.pop(name, None)
            self._next_seq.pop(name, None)
            if self.root:
                p = self._path(name)
                if os.path.exists(p):
                    os.remove(p)
                shutil.rmtree(self._state_dir(name), ignore_errors=True)

    # -- trials -------------------------------------------------------------

    def create_trial(self, trial: Trial) -> Trial:
        with self._lock:
            exp_trials = self._trials.setdefault(trial.experiment_name, {})
            if trial.name in exp_trials:
                raise ValueError(f"trial {trial.name!r} already exists")
            exp_trials[trial.name] = trial
            nxt = self._next_seq.get(trial.experiment_name, 0)
            self._trial_seq.setdefault(trial.experiment_name, {})[trial.name] = nxt
            self._next_seq[trial.experiment_name] = nxt + 1
            self._persist_trial(trial)
            return trial

    def get_trial(self, experiment_name: str, trial_name: str) -> Optional[Trial]:
        with self._lock:
            return self._trials.get(experiment_name, {}).get(trial_name)

    def list_trials(self, experiment_name: str) -> List[Trial]:
        """Label-selector list in the reference (experiment_controller.go:263);
        returned in creation order."""
        with self._lock:
            return list(self._trials.get(experiment_name, {}).values())

    def update_trial(self, trial: Trial) -> None:
        with self._lock:
            self._trials.setdefault(trial.experiment_name, {})[trial.name] = trial
            self._persist_trial(trial)

    def delete_trial(self, experiment_name: str, trial_name: str) -> None:
        with self._lock:
            self._trials.get(experiment_name, {}).pop(trial_name, None)
            self._trial_seq.get(experiment_name, {}).pop(trial_name, None)
            self._unlink_trial(experiment_name, trial_name)

    # -- suggestion state ----------------------------------------------------

    def get_suggestion(self, experiment_name: str) -> Optional[SuggestionState]:
        with self._lock:
            return self._suggestions.get(experiment_name)

    def put_suggestion(self, s: SuggestionState) -> None:
        with self._lock:
            self._suggestions[s.experiment_name] = s
            self._persist_suggestion(s.experiment_name)

    def delete_suggestion(self, experiment_name: str) -> None:
        with self._lock:
            self._suggestions.pop(experiment_name, None)
            self._persist_suggestion(experiment_name)

    # -- trial templates ------------------------------------------------------
    # Reference: the UI's trial-template configmap CRUD
    # (pkg/ui/v1beta1/backend.go template endpoints); here templates are
    # TrialTemplate JSON dicts persisted under <root>/templates/.

    def put_template(self, name: str, template: dict) -> None:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"invalid template name {name!r}")
        with self._lock:
            self._templates[name] = template
            if self.root:
                d = os.path.join(self.root, "templates")
                os.makedirs(d, exist_ok=True)
                tmp = os.path.join(d, f"{name}.json.tmp{os.getpid()}")
                with open(tmp, "w") as f:
                    json.dump(template, f, indent=2)
                os.replace(tmp, os.path.join(d, name + ".json"))

    def get_template(self, name: str) -> Optional[dict]:
        with self._lock:
            return self._templates.get(name)

    def list_templates(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._templates)

    def delete_template(self, name: str) -> None:
        with self._lock:
            self._templates.pop(name, None)
            if self.root:
                p = os.path.join(self.root, "templates", name + ".json")
                if os.path.exists(p):
                    os.remove(p)

    def _load_templates(self) -> None:
        d = os.path.join(self.root, "templates")
        if not os.path.isdir(d):
            return
        for fn in os.listdir(d):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(d, fn)) as f:
                        template = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                with self._lock:
                    self._templates[fn[:-5]] = template

    # -- persistence ---------------------------------------------------------

    def _path(self, name: str) -> str:
        """Legacy (pre-round-4) single-file snapshot, read-only now."""
        assert self.root is not None
        return os.path.join(self.root, name, "state.json")

    def _state_dir(self, name: str) -> str:
        assert self.root is not None
        return os.path.join(self.root, name, "state")

    @staticmethod
    def _write_record(path: str, payload: dict) -> None:
        """One atomic record write: a single buffered write of the serialized
        form (json.dump's many tiny stream writes dominate the profile),
        then rename. The tmp name is pid-unique: the placement lease makes
        each experiment single-writer across replicas, but a failover
        hand-off can overlap the old incarnation's last write with the new
        owner's first — colliding staging files must never truncate each
        other mid-serialize (os.replace keeps the install itself atomic)."""
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(payload))
        os.replace(tmp, path)

    def _persist(self, name: str) -> None:
        """Persist the experiment record (and the save stamp). Trial and
        suggestion records have their own writers; this no longer rewrites
        them."""
        if not self.root:
            return
        exp = self._experiments.get(name)
        if exp is None:
            return
        d = self._state_dir(name)
        os.makedirs(d, exist_ok=True)
        payload = exp.to_dict()
        payload["savedAt"] = time.time()
        self._write_record(os.path.join(d, "experiment.json"), payload)

    def _persist_trial(self, trial: Trial) -> None:
        if not self.root or trial.experiment_name not in self._experiments:
            return
        d = os.path.join(self._state_dir(trial.experiment_name), "trials")
        os.makedirs(d, exist_ok=True)
        payload = trial.to_dict()
        # creation order matters (list_trials contract) but isn't a Trial
        # field; stamp the store's monotonic per-experiment counter into the
        # record for load() to sort by (filenames sort by the random name
        # suffix, and a live dict index would be reused after deletes)
        payload["_seq"] = self._trial_seq.get(trial.experiment_name, {}).get(
            trial.name, len(self._trials.get(trial.experiment_name, {}))
        )
        self._write_record(os.path.join(d, trial.name + ".json"), payload)

    def _unlink_trial(self, experiment_name: str, trial_name: str) -> None:
        if not self.root:
            return
        p = os.path.join(self._state_dir(experiment_name), "trials", trial_name + ".json")
        if os.path.exists(p):
            os.remove(p)

    def _persist_suggestion(self, experiment_name: str) -> None:
        if not self.root or experiment_name not in self._experiments:
            return
        d = self._state_dir(experiment_name)
        os.makedirs(d, exist_ok=True)
        p = os.path.join(d, "suggestion.json")
        s = self._suggestions.get(experiment_name)
        if s is None:
            if os.path.exists(p):
                os.remove(p)
            return
        self._write_record(p, s.to_dict())

    def has_state(self, name: str) -> bool:
        """True when a persisted snapshot (either layout) exists for load()."""
        if not self.root:
            return False
        return (
            os.path.exists(os.path.join(self._state_dir(name), "experiment.json"))
            or os.path.exists(self._path(name))
        )

    def load(self, name: str) -> Optional[Experiment]:
        """FromVolume resume: restore experiment + trials + suggestion state.

        Prefers the per-record layout; falls back to the legacy single-file
        snapshot so stores written by earlier rounds still resume.
        """
        if not self.root:
            return None
        d = self._state_dir(name)
        exp_p = os.path.join(d, "experiment.json")
        if os.path.exists(exp_p):
            with open(exp_p) as f:
                exp_d = json.load(f)
            loaded = []
            tdir = os.path.join(d, "trials")
            if os.path.isdir(tdir):
                for fn in os.listdir(tdir):
                    if not fn.endswith(".json"):
                        continue
                    try:
                        with open(os.path.join(tdir, fn)) as f:
                            rec = json.load(f)
                        loaded.append((rec.pop("_seq", 1 << 30), Trial.from_dict(rec)))
                    except (OSError, ValueError, KeyError):
                        continue  # a torn record loses one trial, not the run
            loaded.sort(key=lambda st: (st[0], st[1].name))
            trials: Dict[str, Trial] = {t.name: t for _, t in loaded}
            seqs = {t.name: s for s, t in loaded if s < (1 << 30)}
            suggestion = None
            sp = os.path.join(d, "suggestion.json")
            if os.path.exists(sp):
                try:
                    with open(sp) as f:
                        suggestion = SuggestionState.from_dict(json.load(f))
                except (OSError, ValueError, KeyError):
                    suggestion = None
            with self._lock:
                exp = Experiment.from_dict(exp_d)
                self._experiments[name] = exp
                self._trials[name] = trials
                self._trial_seq[name] = seqs
                self._next_seq[name] = max(seqs.values(), default=-1) + 1
                if suggestion is not None:
                    self._suggestions[name] = suggestion
                return exp
        p = self._path(name)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            payload = json.load(f)
        with self._lock:
            exp = Experiment.from_dict(payload["experiment"])
            self._experiments[name] = exp
            self._trials[name] = {t["name"]: Trial.from_dict(t) for t in payload.get("trials", [])}
            self._trial_seq[name] = {
                tn: i for i, tn in enumerate(self._trials[name])
            }
            self._next_seq[name] = len(self._trials[name])
            if payload.get("suggestion"):
                self._suggestions[name] = SuggestionState.from_dict(payload["suggestion"])
            # migrate: a legacy monolith loads once; without re-persisting,
            # the next process would prefer the (trial-less) per-record dir
            # the first reconcile creates and silently drop completed work.
            # experiment.json goes LAST — its presence is what makes load()
            # prefer the per-record dir, so a crash mid-migration leaves the
            # monolith authoritative instead of a half-written record set.
            for t in self._trials[name].values():
                self._persist_trial(t)
            self._persist_suggestion(name)
            self._persist(name)
            return exp

    def persisted_experiments(self) -> List[str]:
        """Names with a loadable snapshot under the root (either layout) —
        the offline-inspection walk `katib-tpu recover` and `list` use."""
        if not self.root or not os.path.isdir(self.root):
            return []
        return sorted(
            name
            for name in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, name)) and self.has_state(name)
        )

    def experiment_dir(self, name: str) -> Optional[str]:
        if not self.root:
            return None
        d = os.path.join(self.root, name)
        os.makedirs(d, exist_ok=True)
        return d
