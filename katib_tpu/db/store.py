"""Observation-log store — the data plane.

TPU-native replacement for katib-db-manager + MySQL/Postgres:
- gRPC surface: reference pkg/apis/manager/v1beta1/api.proto:13-31
  (ReportObservationLog / GetObservationLog / DeleteObservationLog)
- table schema: reference pkg/db/v1beta1/mysql/mysql.go:67-166
  (observation_logs(trial_name, time, metric_name, value))
- interface: reference pkg/db/v1beta1/common/kdb.go

Backed by SQLite in WAL mode: one writer per experiment host, many readers —
matching the reference's single-db-manager topology without a network hop.
A thread-safe in-memory implementation backs unit tests.

Folding an observation log into per-metric {min,max,latest} honoring
timestamps mirrors trial_controller_util.go:165-217 (getMetrics).
"""

from __future__ import annotations

import math
import os
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..api.spec import (
    UNAVAILABLE_METRIC_VALUE,
    Metric,
    MetricStrategyType,
    Observation,
    ObjectiveSpec,
)


@dataclass
class MetricLog:
    """One observation-log row: (timestamp, metric_name, value).

    Values are stored as strings like the reference (mysql.go VARCHAR value) so
    non-numeric reports surface as 'unavailable' rather than crashing.
    """

    timestamp: float
    metric_name: str
    value: str


class ObservationStore:
    """Abstract store interface, reference kdb.go:1-30."""

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        raise NotImplementedError

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
    ) -> List[MetricLog]:
        raise NotImplementedError

    def delete_observation_log(self, trial_name: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryObservationStore(ObservationStore):
    """Thread-safe dict-backed store for tests and in-process experiments."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logs: Dict[str, List[MetricLog]] = {}

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        with self._lock:
            self._logs.setdefault(trial_name, []).extend(logs)

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
    ) -> List[MetricLog]:
        with self._lock:
            rows = list(self._logs.get(trial_name, []))
        return _filter_logs(rows, metric_name, start_time, end_time)

    def delete_observation_log(self, trial_name: str) -> None:
        with self._lock:
            self._logs.pop(trial_name, None)


class SqliteObservationStore(ObservationStore):
    """SQLite-WAL store; schema mirrors mysql.go observation_logs."""

    def __init__(self, path: str) -> None:
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS observation_logs ("
                " trial_name TEXT NOT NULL,"
                " time REAL NOT NULL,"
                " metric_name TEXT NOT NULL,"
                " value TEXT NOT NULL)"
            )
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_obs_trial ON observation_logs(trial_name, time)"
            )
            self._conn.commit()

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT INTO observation_logs(trial_name, time, metric_name, value) VALUES (?,?,?,?)",
                [(trial_name, l.timestamp, l.metric_name, l.value) for l in logs],
            )
            self._conn.commit()

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
    ) -> List[MetricLog]:
        q = "SELECT time, metric_name, value FROM observation_logs WHERE trial_name = ?"
        args: List = [trial_name]
        if metric_name is not None:
            q += " AND metric_name = ?"
            args.append(metric_name)
        if start_time is not None:
            q += " AND time >= ?"
            args.append(start_time)
        if end_time is not None:
            q += " AND time <= ?"
            args.append(end_time)
        q += " ORDER BY time ASC"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [MetricLog(timestamp=r[0], metric_name=r[1], value=r[2]) for r in rows]

    def delete_observation_log(self, trial_name: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM observation_logs WHERE trial_name = ?", (trial_name,))
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def _filter_logs(
    rows: List[MetricLog],
    metric_name: Optional[str],
    start_time: Optional[float],
    end_time: Optional[float],
) -> List[MetricLog]:
    out = rows
    if metric_name is not None:
        out = [r for r in out if r.metric_name == metric_name]
    if start_time is not None:
        out = [r for r in out if r.timestamp >= start_time]
    if end_time is not None:
        out = [r for r in out if r.timestamp <= end_time]
    return sorted(out, key=lambda r: r.timestamp)


def _parse_float(value: str) -> Optional[float]:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    if math.isnan(f):
        return None
    return f


def fold_observation(logs: Sequence[MetricLog], metric_names: Sequence[str]) -> Observation:
    """Fold raw logs into per-metric {min,max,latest}.

    Mirrors getMetrics (trial_controller_util.go:165-217): 'latest' is the
    value with the greatest timestamp (ties: last reported); min/max ignore
    non-numeric values; a metric with no parseable value at all reports
    'unavailable' everywhere.
    """
    metrics: List[Metric] = []
    for name in metric_names:
        rows = [r for r in logs if r.metric_name == name]
        latest: str = UNAVAILABLE_METRIC_VALUE
        best_ts = -math.inf
        lo = math.inf
        hi = -math.inf
        has_numeric = False
        for r in rows:
            if r.timestamp >= best_ts:
                best_ts = r.timestamp
                latest = r.value
            f = _parse_float(r.value)
            if f is not None:
                has_numeric = True
                lo = min(lo, f)
                hi = max(hi, f)
        if not rows:
            metrics.append(Metric(name=name))
            continue
        metrics.append(
            Metric(
                name=name,
                min=repr(lo) if has_numeric else UNAVAILABLE_METRIC_VALUE,
                max=repr(hi) if has_numeric else UNAVAILABLE_METRIC_VALUE,
                latest=latest,
            )
        )
    return Observation(metrics=metrics)


def objective_value(
    observation: Optional[Observation], objective: ObjectiveSpec
) -> Optional[float]:
    """Extract the objective metric per its strategy.

    Mirrors getObjectiveMetricValue (status_util.go:153-184).
    """
    if observation is None:
        return None
    m = observation.metric(objective.objective_metric_name)
    if m is None:
        return None
    strategy = objective.strategy_for(objective.objective_metric_name)
    raw = {
        MetricStrategyType.MIN: m.min,
        MetricStrategyType.MAX: m.max,
        MetricStrategyType.LATEST: m.latest,
    }[strategy]
    return _parse_float(raw)


def observation_available(
    observation: Optional[Observation], objective: ObjectiveSpec
) -> bool:
    """Latest-value availability of the objective metric — the predicate the
    experiment controller's request math uses to exclude incomplete
    early-stopped trials (experiment_controller.go:449-461). Hyperband's
    full-width guard MUST use this same predicate: if the two ever disagreed
    for a trial, the guard's expected width would permanently exceed the
    controller's request and the experiment would stall (ADVICE r2)."""
    if observation is None:
        return False
    m = observation.metric(objective.objective_metric_name)
    return m is not None and m.latest != UNAVAILABLE_METRIC_VALUE


def obs_db_path(root: Optional[str]) -> Optional[str]:
    """Canonical observation-log DB location under a state root."""
    import os

    return os.path.join(root, "observations.db") if root else None


def open_store(path: Optional[str], backend: str = "auto") -> ObservationStore:
    """Factory, reference pkg/db/v1beta1/db.go (driver selection by env).

    backend: 'auto' (sqlite, or $KATIB_TPU_OBSLOG_BACKEND override),
    'sqlite', 'memory', or 'native' (C++ engine, katib_tpu/native/obslog.cc —
    single-writer-process; subprocess trials must push via gRPC or stdout
    rather than opening the same file).
    """
    import os

    if backend == "auto":
        backend = os.environ.get("KATIB_TPU_OBSLOG_BACKEND", "sqlite")
    if path is None or backend == "memory":
        return InMemoryObservationStore()
    if backend == "native":
        from ..native.obslog_store import open_native_store

        store = open_native_store(path + ".ktob")
        if store is not None:
            return store
        backend = "sqlite"  # toolchain unavailable: fall back
    return SqliteObservationStore(path)
