"""Observation-log store — the data plane.

TPU-native replacement for katib-db-manager + MySQL/Postgres:
- gRPC surface: reference pkg/apis/manager/v1beta1/api.proto:13-31
  (ReportObservationLog / GetObservationLog / DeleteObservationLog)
- table schema: reference pkg/db/v1beta1/mysql/mysql.go:67-166
  (observation_logs(trial_name, time, metric_name, value))
- interface: reference pkg/db/v1beta1/common/kdb.go

Backed by SQLite in WAL mode: one writer per experiment host, many readers —
matching the reference's single-db-manager topology without a network hop.
A thread-safe in-memory implementation backs unit tests.

Folding an observation log into per-metric {min,max,latest} honoring
timestamps mirrors trial_controller_util.go:165-217 (getMetrics).

Two throughput layers sit on top of the row stores (docs/data-plane.md):

- :class:`BufferedObservationStore` — a group-commit write-behind wrapper.
  ``report_observation_log`` appends to a bounded in-memory queue and
  returns; a background flusher drains the queue into ONE transaction per
  batch (``report_many``). Podracer-style decoupling (arXiv:2104.06272): the
  trial hot loop never waits on an fsync. Reads merge the pending buffer
  (read-your-writes), ``flush()`` is an explicit durability barrier, and a
  full buffer applies backpressure instead of growing without bound.
- an **incremental fold index**: running {min, max, latest, latest_ts} per
  (trial, metric) maintained on append, so ``folded()`` answers the
  getMetrics fold in O(metrics) instead of re-scanning O(rows × metrics).
  ``fold_observation`` over the raw rows remains the fallback/verification
  path; the two are property-tested byte-identical
  (tests/test_obslog_pipeline.py).
"""

from __future__ import annotations

import logging
import math
import os
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..api.spec import (
    UNAVAILABLE_METRIC_VALUE,
    Metric,
    MetricStrategyType,
    Observation,
    ObjectiveSpec,
)

log = logging.getLogger("katib_tpu.obslog")


@dataclass
class HistoryPoint:
    """One completed observation in the transfer-HPO index (ISSUE 10):
    the trial's unit-cube encoding and raw objective value, keyed in the
    store by the owning experiment's search-space signature so future
    experiments over the same space can warm-start from it."""

    experiment: str
    x: List[float]
    y: float


@dataclass
class MetricLog:
    """One observation-log row: (timestamp, metric_name, value).

    Values are stored as strings like the reference (mysql.go VARCHAR value) so
    non-numeric reports surface as 'unavailable' rather than crashing.
    """

    timestamp: float
    metric_name: str
    value: str


class ObservationStore:
    """Abstract store interface, reference kdb.go:1-30."""

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        raise NotImplementedError

    def report_many(self, entries: Sequence[Tuple[str, Sequence[MetricLog]]]) -> None:
        """Append several trials' rows in one call — the group-commit unit.
        Backends that can batch (SQLite: one transaction) override this;
        the default preserves per-trial append semantics."""
        for trial_name, logs in entries:
            if logs:
                self.report_observation_log(trial_name, logs)

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[MetricLog]:
        raise NotImplementedError

    def folded(self, trial_name: str, metric_names: Sequence[str]) -> Observation:
        """Per-metric {min,max,latest} for this trial. The base path re-reads
        and re-folds the raw log (O(rows × metrics)); stores with an
        incremental fold index answer in O(metrics)."""
        return fold_observation(self.get_observation_log(trial_name), metric_names)

    def delete_observation_log(self, trial_name: str) -> None:
        raise NotImplementedError

    def truncate_observation_log(self, trial_name: str, after_time: float) -> int:
        """Crash recovery (controller/recovery.py): drop only the rows
        STRICTLY NEWER than ``after_time`` — the un-checkpointed tail a
        resumed trial will re-report — and return how many were dropped.
        Base implementation reads, deletes, and re-appends the kept prefix
        so every backend (native engine, RPC remotes) inherits correct
        semantics; SQLite overrides with a single ranged DELETE."""
        rows = self.get_observation_log(trial_name)
        kept = [r for r in rows if r.timestamp <= after_time]
        dropped = len(rows) - len(kept)
        if dropped:
            self.delete_observation_log(trial_name)
            if kept:
                self.report_observation_log(trial_name, kept)
        return dropped

    # -- transfer-HPO index (ISSUE 10) ---------------------------------------
    # Completed experiments are indexed by search-space signature so a new
    # experiment over a matching space can warm-start its suggester from
    # history instead of a cold random phase. Default no-ops keep backends
    # without an index (native engine, RPC remotes) valid.

    def replace_experiment_history(
        self,
        experiment: str,
        signature: str,
        points: Sequence[Tuple[Sequence[float], float]],
    ) -> None:
        """Replace the experiment's indexed observations (idempotent across
        repeat completions/restarts)."""

    def matching_history(
        self,
        signature: str,
        exclude_experiment: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[HistoryPoint]:
        """Indexed observations of OTHER experiments with this signature,
        deterministically ordered (stable across calls so warm-started
        suggestions stay reproducible)."""
        return []

    def delete_experiment_history(self, experiment: str) -> None:
        """Drop the experiment's indexed observations (experiment delete)."""

    def flush(self) -> None:
        """Durability barrier: returns once every previously-appended row is
        persisted in the backing store. No-op for synchronous stores."""

    def close(self) -> None:
        pass


class InMemoryObservationStore(ObservationStore):
    """Thread-safe dict-backed store for tests and in-process experiments."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logs: Dict[str, List[MetricLog]] = {}
        # experiment -> (signature, ordered points); insertion order is the
        # stable "oldest-indexed first" order matching_history promises
        self._history: Dict[str, Tuple[str, List[HistoryPoint]]] = {}

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        with self._lock:
            self._logs.setdefault(trial_name, []).extend(logs)

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[MetricLog]:
        with self._lock:
            rows = list(self._logs.get(trial_name, []))
        out = _filter_logs(rows, metric_name, start_time, end_time)
        return out[:limit] if limit is not None else out

    def delete_observation_log(self, trial_name: str) -> None:
        with self._lock:
            self._logs.pop(trial_name, None)

    def replace_experiment_history(self, experiment, signature, points) -> None:
        rows = [
            HistoryPoint(experiment=experiment, x=[float(v) for v in x], y=float(y))
            for x, y in points
        ]
        with self._lock:
            self._history[experiment] = (signature, rows)

    def matching_history(self, signature, exclude_experiment=None, limit=None):
        with self._lock:
            out: List[HistoryPoint] = []
            for exp in sorted(self._history):
                sig, rows = self._history[exp]
                if sig != signature or exp == exclude_experiment:
                    continue
                out.extend(rows)
        return out[:limit] if limit is not None else out

    def delete_experiment_history(self, experiment: str) -> None:
        with self._lock:
            self._history.pop(experiment, None)


class SqlObservationStore(ObservationStore):
    """Row store over one :class:`~katib_tpu.db.dialects.SqlDialect`.

    The store body is engine-free: every query is written in canonical
    qmark style and routed through ``dialect.sql()``; schema DDL, session
    setup, transaction begin, and the busy/retry predicate come from the
    dialect (ISSUE 17's pluggable-store seam). Hardened for CROSS-PROCESS
    multi-writer access (the sharded control plane: N replica processes +
    their trial subprocesses share one engine, each with its own
    connection):

    - engine-side parking first (SQLite ``busy_timeout``, Postgres lock
      waits), so a write that lands while another process holds the write
      lock waits instead of failing instantly;
    - a bounded retry loop (:meth:`_retry_locked`) around every statement
      batch — a genuinely saturated writer surfaces as a few jittered
      retries, not an exception thrown through the
      BufferedObservationStore durability barrier.
    """

    BUSY_TIMEOUT_MS = 10_000
    BUSY_RETRIES = 5
    BUSY_RETRY_SLEEP_S = 0.05

    def __init__(self, dialect) -> None:
        self.dialect = dialect
        self.path = getattr(dialect, "path", None)
        if self.path:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._conn = dialect.connect()
        with self._lock:
            self.dialect.on_connect(self._conn)
            for stmt in self.dialect.schema():
                self._conn.execute(stmt)
            self._conn.commit()

    def _sql(self, query: str) -> str:
        return self.dialect.sql(query)

    def _retry_locked(self, fn):
        """Run one statement batch, retrying engine-busy errors
        (``dialect.is_busy``) with linear backoff (caller holds
        ``self._lock``; the contention being absorbed is CROSS-process —
        another replica's write transaction or an external reader pinning
        the engine). Anything else raises through."""
        last: Optional[BaseException] = None
        for attempt in range(self.BUSY_RETRIES):
            try:
                return fn()
            except Exception as e:
                if not self.dialect.is_busy(e):
                    raise
                last = e
                try:
                    self._conn.rollback()
                except Exception:
                    pass
                time.sleep(self.BUSY_RETRY_SLEEP_S * (attempt + 1))
        raise last

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        rows = [(trial_name, l.timestamp, l.metric_name, l.value) for l in logs]
        q = self._sql(
            "INSERT INTO observation_logs(trial_name, time, metric_name, value) VALUES (?,?,?,?)"
        )

        def _write():
            self._conn.executemany(q, rows)
            self._conn.commit()

        with self._lock:
            self._retry_locked(_write)

    def report_many(self, entries: Sequence[Tuple[str, Sequence[MetricLog]]]) -> None:
        """Group commit: every trial's rows in ONE explicit transaction —
        one fsync for the whole drained batch instead of one per report.
        An engine-busy error (a concurrent replica's writer, an external
        reader) retries the whole transaction rather than raising through
        the buffered store's durability barrier."""
        rows = [
            (trial_name, l.timestamp, l.metric_name, l.value)
            for trial_name, logs in entries
            for l in logs
        ]
        if not rows:
            return
        q = self._sql(
            "INSERT INTO observation_logs(trial_name, time, metric_name, value)"
            " VALUES (?,?,?,?)"
        )

        def _write():
            self.dialect.begin(self._conn)
            try:
                self._conn.executemany(q, rows)
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise

        with self._lock:
            self._retry_locked(_write)

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[MetricLog]:
        q = "SELECT time, metric_name, value FROM observation_logs WHERE trial_name = ?"
        args: List = [trial_name]
        if metric_name is not None:
            q += " AND metric_name = ?"
            args.append(metric_name)
        if start_time is not None:
            q += " AND time >= ?"
            args.append(start_time)
        if end_time is not None:
            q += " AND time <= ?"
            args.append(end_time)
        q += " ORDER BY time ASC"
        if limit is not None:
            q += " LIMIT ?"
            args.append(int(limit))
        with self._lock:
            rows = self._conn.execute(self._sql(q), args).fetchall()
        return [MetricLog(timestamp=r[0], metric_name=r[1], value=r[2]) for r in rows]

    def delete_observation_log(self, trial_name: str) -> None:
        q = self._sql("DELETE FROM observation_logs WHERE trial_name = ?")

        def _write():
            self._conn.execute(q, (trial_name,))
            self._conn.commit()

        with self._lock:
            self._retry_locked(_write)

    def truncate_observation_log(self, trial_name: str, after_time: float) -> int:
        q = self._sql("DELETE FROM observation_logs WHERE trial_name = ? AND time > ?")

        def _write():
            cur = self._conn.execute(q, (trial_name, after_time))
            self._conn.commit()
            return int(cur.rowcount or 0)

        with self._lock:
            return self._retry_locked(_write)

    def replace_experiment_history(self, experiment, signature, points) -> None:
        import json as _json

        now = time.time()
        rows = [
            (experiment, signature, now, _json.dumps([float(v) for v in x]), float(y))
            for x, y in points
        ]
        with self._lock:
            self._conn.execute(
                self._sql("DELETE FROM experiment_history WHERE experiment = ?"),
                (experiment,),
            )
            if rows:
                self._conn.executemany(
                    self._sql(
                        "INSERT INTO experiment_history(experiment, signature, time, x, y)"
                        " VALUES (?,?,?,?,?)"
                    ),
                    rows,
                )
            self._conn.commit()

    def matching_history(self, signature, exclude_experiment=None, limit=None):
        import json as _json

        q = "SELECT experiment, x, y FROM experiment_history WHERE signature = ?"
        args: List = [signature]
        if exclude_experiment is not None:
            q += " AND experiment != ?"
            args.append(exclude_experiment)
        q += f" ORDER BY time ASC, {self.dialect.history_tiebreaker} ASC"
        if limit is not None:
            q += " LIMIT ?"
            args.append(int(limit))
        with self._lock:
            rows = self._conn.execute(self._sql(q), args).fetchall()
        return [
            HistoryPoint(experiment=r[0], x=[float(v) for v in _json.loads(r[1])], y=r[2])
            for r in rows
        ]

    def delete_experiment_history(self, experiment: str) -> None:
        with self._lock:
            self._conn.execute(
                self._sql("DELETE FROM experiment_history WHERE experiment = ?"),
                (experiment,),
            )
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class SqliteObservationStore(SqlObservationStore):
    """SQLite-WAL store; schema mirrors mysql.go observation_logs.

    The historical default engine, now a one-line binding of
    :class:`SqlObservationStore` to the SQLite dialect — same pragmas,
    same DDL strings, same busy/retry behavior as before the seam."""

    def __init__(self, path: str, busy_timeout_ms: Optional[int] = None) -> None:
        from .dialects import SqliteDialect

        super().__init__(SqliteDialect(path, busy_timeout_ms=busy_timeout_ms))


class _FoldEntry:
    """Running fold state for one (trial, metric): updated on append, read by
    folded(). Mirrors the fold_observation scan exactly — 'latest' is the
    last-appended value among the max-timestamp rows, min/max ignore
    non-numeric values."""

    __slots__ = ("count", "lo", "hi", "has_numeric", "latest", "best_ts")

    def __init__(self) -> None:
        self.count = 0
        self.lo = math.inf
        self.hi = -math.inf
        self.has_numeric = False
        self.latest: str = UNAVAILABLE_METRIC_VALUE
        self.best_ts = -math.inf

    def add(self, row: MetricLog) -> None:
        self.count += 1
        if row.timestamp >= self.best_ts:
            self.best_ts = row.timestamp
            self.latest = row.value
        f = _parse_float(row.value)
        if f is not None:
            self.has_numeric = True
            self.lo = min(self.lo, f)
            self.hi = max(self.hi, f)

    def metric(self, name: str) -> Metric:
        if self.count == 0:
            return Metric(name=name)
        return Metric(
            name=name,
            min=repr(self.lo) if self.has_numeric else UNAVAILABLE_METRIC_VALUE,
            max=repr(self.hi) if self.has_numeric else UNAVAILABLE_METRIC_VALUE,
            latest=self.latest,
        )


class BufferedObservationStore(ObservationStore):
    """Write-behind wrapper: bounded buffer + background group commit.

    Contract (docs/data-plane.md):

    - **append is cheap**: ``report_observation_log`` enqueues and returns;
      the flusher thread drains everything pending into one
      ``inner.report_many`` transaction per batch.
    - **read-your-writes**: reads merge the pending/in-flight buffer, so
      callers (observation folds, early stopping, the UI) never observe a
      gap between an acknowledged report and the durable log.
    - **bounded**: at most ``max_buffered_rows`` rows buffer; a producer
      hitting the bound blocks until the flusher drains (backpressure, not
      unbounded memory). A single oversized batch is admitted alone.
    - **flush() barrier**: returns once every row appended before the call
      is durable in ``inner`` — the hook MetricsReporter uses before
      raising TrialPreempted/TrialKilled so a requeued victim loses
      nothing.
    - **incremental fold index**: folded() answers from running per-(trial,
      metric) state seeded lazily from pre-existing rows on first touch.
      Single-writer per db file, like the WAL topology it wraps.

    A flusher write failure is recorded and re-raised from the next
    flush()/report (loud, not silent); the failed batch is dropped.
    """

    def __init__(
        self,
        inner: ObservationStore,
        max_buffered_rows: int = 8192,
        flush_interval: float = 0.05,
        metrics=None,
    ) -> None:
        self.inner = inner
        self.max_buffered_rows = max(1, int(max_buffered_rows))
        self.flush_interval = flush_interval
        self.metrics_registry = metrics
        self._cv = threading.Condition()
        # serializes reads against an in-flight group commit so the merged
        # (buffer + inner) view never duplicates or drops the moving batch
        self._io_lock = threading.Lock()
        self._pending: List[Tuple[str, List[MetricLog]]] = []
        self._pending_rows = 0
        self._inflight: List[Tuple[str, List[MetricLog]]] = []
        self._inflight_rows = 0
        self._seq = 0          # rows accepted
        self._durable_seq = 0  # rows handed off to inner (or dropped on error)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._index: Dict[Tuple[str, str], _FoldEntry] = {}
        self._seeded: set = set()
        self._stats = {
            "flush_total": 0,
            "flush_batch_rows": 0,
            "flush_batch_rows_max": 0,
            "last_flush_seconds": 0.0,
        }
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True, name="obslog-flusher"
        )
        self._flusher.start()

    # -- write path ----------------------------------------------------------

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        self.report_many([(trial_name, logs)])

    def report_many(self, entries: Sequence[Tuple[str, Sequence[MetricLog]]]) -> None:
        batch = [(t, list(ls)) for t, ls in entries if ls]
        n = sum(len(ls) for _, ls in batch)
        if n == 0:
            return
        with self._cv:
            self._raise_error_locked()
            if self._closed:
                raise RuntimeError("observation store is closed")
            # backpressure: wait for the flusher rather than buffer without
            # bound; an oversized batch is admitted once the buffer is empty
            while (
                self._pending_rows + self._inflight_rows + n > self.max_buffered_rows
                and self._pending_rows + self._inflight_rows > 0
            ):
                self._cv.notify_all()
                self._cv.wait(timeout=1.0)
                self._raise_error_locked()
                if self._closed:
                    raise RuntimeError("observation store is closed")
            for trial_name, logs in batch:
                self._seed_locked(trial_name)
                for row in logs:
                    self._index.setdefault(
                        (trial_name, row.metric_name), _FoldEntry()
                    ).add(row)
            self._pending.extend(batch)
            self._pending_rows += n
            self._seq += n
            buffered = self._pending_rows + self._inflight_rows
            self._cv.notify_all()
        if self.metrics_registry is not None:
            self.metrics_registry.set_gauge("katib_obslog_buffered_rows", float(buffered))

    # -- read path -----------------------------------------------------------

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[MetricLog]:
        # _io_lock: no group commit is mid-transaction, so inner ∪ buffer is
        # exactly the full log (no torn batch, no duplicates)
        with self._io_lock:
            with self._cv:
                buffered = [
                    row
                    for t, logs in self._inflight + self._pending
                    if t == trial_name
                    for row in logs
                ]
            # limit pushes down: the true first-k of (inner ∪ buffer) is a
            # subset of (first-k of inner) ∪ buffer, so the merge stays exact
            rows = self.inner.get_observation_log(
                trial_name, metric_name=metric_name,
                start_time=start_time, end_time=end_time, limit=limit,
            )
        if buffered:
            rows = rows + _filter_logs(buffered, metric_name, start_time, end_time)
            rows.sort(key=lambda r: r.timestamp)  # stable: appended-later stays later
        return rows[:limit] if limit is not None else rows

    def folded(self, trial_name: str, metric_names: Sequence[str]) -> Observation:
        with self._cv:
            if trial_name in self._seeded:
                return Observation(
                    metrics=[
                        self._index.get((trial_name, name), _FoldEntry()).metric(name)
                        for name in metric_names
                    ]
                )
        # The index only owns trials whose rows arrive through this wrapper.
        # Anything else (subprocess trials pushing straight into the SQLite
        # file via the env binding) may gain rows the wrapper never sees, so
        # cache nothing and fall back to the verification rescan.
        return fold_observation(self.get_observation_log(trial_name), metric_names)

    def _seed_locked(self, trial_name: str) -> None:
        """First APPEND for a trial through this wrapper: fold rows already
        durable in inner (a store reopened over an existing db, a subprocess
        trial's direct pushes before collection) into the index, then let
        incremental updates own it. Runs before the new rows are applied, so
        buffered rows are never double-counted. Caller holds _cv."""
        if trial_name in self._seeded:
            return
        self._seeded.add(trial_name)
        for row in self.inner.get_observation_log(trial_name):
            self._index.setdefault((trial_name, row.metric_name), _FoldEntry()).add(row)

    # -- lifecycle / barriers ------------------------------------------------

    def delete_observation_log(self, trial_name: str) -> None:
        self.flush()
        with self._io_lock:
            with self._cv:
                for key in [k for k in self._index if k[0] == trial_name]:
                    del self._index[key]
                # back to unowned: the next append re-seeds from inner, the
                # next folded() rescans — external writers stay visible
                self._seeded.discard(trial_name)
            self.inner.delete_observation_log(trial_name)

    def truncate_observation_log(self, trial_name: str, after_time: float) -> int:
        # same invalidation contract as delete: the fold index rebuilds from
        # inner on the trial's next touch, so the truncated tail can't linger
        # in cached min/max/latest state
        self.flush()
        with self._io_lock:
            with self._cv:
                for key in [k for k in self._index if k[0] == trial_name]:
                    del self._index[key]
                self._seeded.discard(trial_name)
            return self.inner.truncate_observation_log(trial_name, after_time)

    def replace_experiment_history(self, experiment, signature, points) -> None:
        # index writes are rare (one batch per completed experiment) and
        # bypass the write-behind buffer: straight through to the backing
        # store, like the schema they share
        self.inner.replace_experiment_history(experiment, signature, points)

    def matching_history(self, signature, exclude_experiment=None, limit=None):
        return self.inner.matching_history(
            signature, exclude_experiment=exclude_experiment, limit=limit
        )

    def delete_experiment_history(self, experiment: str) -> None:
        self.inner.delete_experiment_history(experiment)

    def flush(self) -> None:
        """Block until every row appended before this call is durable."""
        with self._cv:
            target = self._seq
            self._cv.notify_all()
            while self._durable_seq < target:
                if not self._flusher.is_alive():
                    break
                self._cv.wait(timeout=1.0)
            self._raise_error_locked()

    def _raise_error_locked(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"observation-log flush failed: {err}") from err

    def stats(self) -> Dict[str, float]:
        with self._cv:
            out = dict(self._stats)
            out["buffered_rows"] = self._pending_rows + self._inflight_rows
            return out

    def close(self) -> None:
        try:
            self.flush()
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._flusher.join(timeout=5.0)
            self.inner.close()

    # -- flusher -------------------------------------------------------------

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait(timeout=self.flush_interval)
                if not self._pending and self._closed:
                    return
                self._inflight = self._pending
                self._inflight_rows = self._pending_rows
                self._pending = []
                self._pending_rows = 0
                batch, rows = self._inflight, self._inflight_rows
            t0 = time.perf_counter()
            err: Optional[BaseException] = None
            with self._io_lock:
                try:
                    self.inner.report_many(batch)
                except BaseException as e:  # surface via the next barrier
                    err = e
                with self._cv:
                    self._inflight = []
                    self._inflight_rows = 0
                    self._durable_seq += rows
                    if err is not None:
                        self._error = err
                    else:
                        dt = time.perf_counter() - t0
                        self._stats["flush_total"] += 1
                        self._stats["flush_batch_rows"] += rows
                        self._stats["flush_batch_rows_max"] = max(
                            self._stats["flush_batch_rows_max"], rows
                        )
                        self._stats["last_flush_seconds"] = dt
                    buffered = self._pending_rows
                    self._cv.notify_all()
            if err is not None:
                log.error("observation-log group commit failed (%d rows dropped): %s", rows, err)
            elif self.metrics_registry is not None:
                self.metrics_registry.inc("katib_obslog_flush_total")
                self.metrics_registry.inc("katib_obslog_flush_batch_rows", value=float(rows))
                self.metrics_registry.set_gauge(
                    "katib_obslog_flush_latency_seconds", round(dt, 6)
                )
                self.metrics_registry.set_gauge("katib_obslog_buffered_rows", float(buffered))


def _filter_logs(
    rows: List[MetricLog],
    metric_name: Optional[str],
    start_time: Optional[float],
    end_time: Optional[float],
) -> List[MetricLog]:
    out = rows
    if metric_name is not None:
        out = [r for r in out if r.metric_name == metric_name]
    if start_time is not None:
        out = [r for r in out if r.timestamp >= start_time]
    if end_time is not None:
        out = [r for r in out if r.timestamp <= end_time]
    return sorted(out, key=lambda r: r.timestamp)


def _parse_float(value: str) -> Optional[float]:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    if math.isnan(f):
        return None
    return f


def fold_observation(logs: Sequence[MetricLog], metric_names: Sequence[str]) -> Observation:
    """Fold raw logs into per-metric {min,max,latest}.

    Mirrors getMetrics (trial_controller_util.go:165-217): 'latest' is the
    value with the greatest timestamp (ties: last reported); min/max ignore
    non-numeric values; a metric with no parseable value at all reports
    'unavailable' everywhere.

    Single pass over the rows building every requested metric at once (the
    old shape rescanned the full row list once per metric name). This is
    the fallback/verification path for stores without the incremental fold
    index; BufferedObservationStore.folded must stay byte-identical to it.
    """
    entries: Dict[str, _FoldEntry] = {name: _FoldEntry() for name in metric_names}
    for row in logs:
        entry = entries.get(row.metric_name)
        if entry is not None:
            entry.add(row)
    return Observation(
        metrics=[entries[name].metric(name) for name in metric_names]
    )


def objective_value(
    observation: Optional[Observation], objective: ObjectiveSpec
) -> Optional[float]:
    """Extract the objective metric per its strategy.

    Mirrors getObjectiveMetricValue (status_util.go:153-184).
    """
    if observation is None:
        return None
    m = observation.metric(objective.objective_metric_name)
    if m is None:
        return None
    strategy = objective.strategy_for(objective.objective_metric_name)
    raw = {
        MetricStrategyType.MIN: m.min,
        MetricStrategyType.MAX: m.max,
        MetricStrategyType.LATEST: m.latest,
    }[strategy]
    return _parse_float(raw)


def observation_available(
    observation: Optional[Observation], objective: ObjectiveSpec
) -> bool:
    """Latest-value availability of the objective metric — the predicate the
    experiment controller's request math uses to exclude incomplete
    early-stopped trials (experiment_controller.go:449-461). Hyperband's
    full-width guard MUST use this same predicate: if the two ever disagreed
    for a trial, the guard's expected width would permanently exceed the
    controller's request and the experiment would stall (ADVICE r2)."""
    if observation is None:
        return False
    m = observation.metric(objective.objective_metric_name)
    return m is not None and m.latest != UNAVAILABLE_METRIC_VALUE


def obs_db_path(root: Optional[str]) -> Optional[str]:
    """Canonical observation-log DB location under a state root."""
    import os

    return os.path.join(root, "observations.db") if root else None


def open_store(path: Optional[str], backend: str = "auto") -> ObservationStore:
    """Factory, reference pkg/db/v1beta1/db.go (driver selection by env).

    backend: 'auto' (sqlite, or $KATIB_TPU_OBSLOG_BACKEND override;
    $KATIB_TPU_PG_DSN promotes auto/sqlite to 'postgres'), 'sqlite',
    'postgres' (db/dialects.py seam — requires an installed driver),
    'memory', or 'native' (C++ engine, katib_tpu/native/obslog.cc —
    single-writer-process; subprocess trials must push via gRPC or stdout
    rather than opening the same file).

    The controller wraps the SQL store in BufferedObservationStore
    (ExperimentController, config runtime.obslog_buffered); subprocess env
    bindings and the native engine keep their direct-write paths.
    """
    import os

    if backend == "auto":
        backend = os.environ.get("KATIB_TPU_OBSLOG_BACKEND", "sqlite")
    pg_dsn = os.environ.get("KATIB_TPU_PG_DSN", "")
    if backend == "postgres" or (backend in ("auto", "sqlite") and pg_dsn):
        from .dialects import PostgresDialect

        return SqlObservationStore(PostgresDialect(pg_dsn))
    if path is None or backend == "memory":
        return InMemoryObservationStore()
    if backend == "native":
        from ..native.obslog_store import open_native_store

        store = open_native_store(path + ".ktob")
        if store is not None:
            return store
        backend = "sqlite"  # toolchain unavailable: fall back
    return SqliteObservationStore(path)
