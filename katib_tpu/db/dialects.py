"""SQL dialect seam for the observation store (ISSUE 17).

Upstream Katib fronts MySQL/Postgres behind the db-manager's
``common/kdb.go`` interface; this module is the same seam one level
lower: everything engine-specific about ``db/store.py`` — placeholder
style, schema DDL, session setup, transaction begin, upsert spelling,
and the busy/retry policy — lives behind :class:`SqlDialect`, so the
group-commit write-behind (PR 3), the fold index, and the framed-ingest
coalescing (PR 16) sit *above* the seam and never change per engine.

Registered dialects:

- ``sqlite`` — the default; byte-identical to the pre-seam store
  (same pragmas, same DDL strings, same busy/retry behavior).
- ``postgres`` — activated by ``KATIB_TPU_PG_DSN``; requires a driver
  (psycopg2 or pg8000) already present in the environment — this repo
  never installs one, so the dialect import-gates and raises a clear
  error when the driver is missing. Conformance tests auto-skip.
- ``fakepg`` — an in-process conformance double: ``format`` (%s)
  paramstyle over a real SQLite file. Its connection REJECTS any
  statement still containing ``?``, proving the store routes every
  query through :meth:`SqlDialect.sql` rather than assuming qmark.
"""

from __future__ import annotations

import sqlite3
from typing import Callable, Dict, List, Optional, Sequence

SQLITE_BUSY_TIMEOUT_MS = 10_000

# the pre-seam schema, verbatim — SqliteDialect must keep emitting these
# exact statements so existing observation.db files open unchanged
_SQLITE_SCHEMA = (
    "CREATE TABLE IF NOT EXISTS observation_logs ("
    " trial_name TEXT NOT NULL,"
    " time REAL NOT NULL,"
    " metric_name TEXT NOT NULL,"
    " value TEXT NOT NULL)",
    "CREATE INDEX IF NOT EXISTS idx_obs_trial ON observation_logs(trial_name, time)",
    # metric-filtered reads (medianstop's first-k objective rows, the
    # CLI --metric tail) hit this instead of scanning the trial range
    "CREATE INDEX IF NOT EXISTS idx_obs_trial_metric"
    " ON observation_logs(trial_name, metric_name, time)",
    # transfer-HPO index (ISSUE 10): completed observations keyed by
    # search-space signature; x is the JSON unit-cube encoding
    "CREATE TABLE IF NOT EXISTS experiment_history ("
    " experiment TEXT NOT NULL,"
    " signature TEXT NOT NULL,"
    " time REAL NOT NULL,"
    " x TEXT NOT NULL,"
    " y REAL NOT NULL)",
    "CREATE INDEX IF NOT EXISTS idx_hist_signature"
    " ON experiment_history(signature, time)",
)

_POSTGRES_SCHEMA = (
    "CREATE TABLE IF NOT EXISTS observation_logs ("
    " trial_name TEXT NOT NULL,"
    " time DOUBLE PRECISION NOT NULL,"
    " metric_name TEXT NOT NULL,"
    " value TEXT NOT NULL)",
    "CREATE INDEX IF NOT EXISTS idx_obs_trial ON observation_logs(trial_name, time)",
    "CREATE INDEX IF NOT EXISTS idx_obs_trial_metric"
    " ON observation_logs(trial_name, metric_name, time)",
    # seq replaces SQLite's implicit rowid as the deterministic
    # matching_history tiebreaker
    "CREATE TABLE IF NOT EXISTS experiment_history ("
    " seq BIGSERIAL,"
    " experiment TEXT NOT NULL,"
    " signature TEXT NOT NULL,"
    " time DOUBLE PRECISION NOT NULL,"
    " x TEXT NOT NULL,"
    " y DOUBLE PRECISION NOT NULL)",
    "CREATE INDEX IF NOT EXISTS idx_hist_signature"
    " ON experiment_history(signature, time)",
)


class SqlDialect:
    """Everything the row store needs to know about one SQL engine.

    The store writes every query in canonical qmark (``?``) style and
    passes it through :meth:`sql` before execution; connections returned
    by :meth:`connect` expose the sqlite3-style convenience surface
    (``execute`` / ``executemany`` / ``commit`` / ``rollback`` /
    ``close``) so the store body stays engine-free.
    """

    name: str = ""
    paramstyle: str = "qmark"
    # column expression breaking ORDER BY time ties deterministically in
    # matching_history (insertion order)
    history_tiebreaker: str = "rowid"

    busy_retries: int = 5
    busy_retry_sleep_s: float = 0.05

    def connect(self):
        raise NotImplementedError

    def on_connect(self, conn) -> None:
        """Per-connection session setup (pragmas, isolation)."""

    def schema(self) -> Sequence[str]:
        raise NotImplementedError

    def sql(self, query: str) -> str:
        """Translate a canonical qmark query to this engine's paramstyle."""
        if self.paramstyle == "qmark":
            return query
        return query.replace("?", "%s")

    def begin(self, conn) -> None:
        """Open an explicit transaction for a group commit."""
        conn.execute(self.sql("BEGIN"))

    def is_busy(self, exc: BaseException) -> bool:
        """True when the statement should be retried (writer contention)."""
        return False

    def upsert(self, table: str, cols: Sequence[str], key_cols: Sequence[str]) -> str:
        """Canonical-qmark INSERT ... ON CONFLICT upsert for this engine
        (both registered engines speak the ON CONFLICT spelling; a MySQL
        dialect would override with ON DUPLICATE KEY UPDATE)."""
        updates = ", ".join(
            f"{c} = excluded.{c}" for c in cols if c not in key_cols
        )
        return (
            f"INSERT INTO {table} ({', '.join(cols)})"
            f" VALUES ({', '.join('?' for _ in cols)})"
            f" ON CONFLICT ({', '.join(key_cols)}) DO UPDATE SET {updates}"
        )


class SqliteDialect(SqlDialect):
    """The default engine — byte-identical to the pre-seam store."""

    name = "sqlite"
    paramstyle = "qmark"
    history_tiebreaker = "rowid"

    def __init__(self, path: str, busy_timeout_ms: Optional[int] = None):
        self.path = path
        self.busy_timeout_ms = busy_timeout_ms or SQLITE_BUSY_TIMEOUT_MS

    def connect(self):
        return sqlite3.connect(
            self.path,
            check_same_thread=False,
            timeout=self.busy_timeout_ms / 1000.0,
        )

    def on_connect(self, conn) -> None:
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute(f"PRAGMA busy_timeout={self.busy_timeout_ms}")

    def schema(self) -> Sequence[str]:
        return _SQLITE_SCHEMA

    def is_busy(self, exc: BaseException) -> bool:
        if not isinstance(exc, sqlite3.OperationalError):
            return False
        msg = str(exc).lower()
        return "locked" in msg or "busy" in msg


class _TranslatingConnection:
    """fakepg's connection: accepts ``format`` (%s) statements, executes
    them on SQLite — and refuses qmark leftovers, so a store statement
    that skipped ``dialect.sql()`` fails the conformance suite loudly."""

    def __init__(self, conn: sqlite3.Connection):
        self._conn = conn

    def _translate(self, query: str) -> str:
        if "?" in query:
            raise AssertionError(
                f"qmark placeholder reached a format-paramstyle dialect: {query!r}"
            )
        return query.replace("%s", "?")

    def execute(self, query: str, args: Sequence = ()):
        return self._conn.execute(self._translate(query), args)

    def executemany(self, query: str, rows: Sequence[Sequence]):
        return self._conn.executemany(self._translate(query), rows)

    def commit(self) -> None:
        self._conn.commit()

    def rollback(self) -> None:
        self._conn.rollback()

    def close(self) -> None:
        self._conn.close()


class FakePostgresDialect(SqliteDialect):
    """Conformance double: a ``format``-paramstyle engine over SQLite.

    Exists so the dialect matrix exercises placeholder translation and
    the seam contract in-process on every CI run, even where no real
    Postgres (or driver) is available.
    """

    name = "fakepg"
    paramstyle = "format"

    def connect(self):
        return _TranslatingConnection(super().connect())

    def on_connect(self, conn) -> None:
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute(f"PRAGMA busy_timeout={self.busy_timeout_ms}")


class _PgConnectionAdapter:
    """DBAPI cursor-per-statement adapter giving psycopg2/pg8000
    connections the sqlite3 convenience surface the store uses."""

    def __init__(self, conn):
        self._conn = conn

    def execute(self, query: str, args: Sequence = ()):
        cur = self._conn.cursor()
        cur.execute(query, tuple(args))
        return cur

    def executemany(self, query: str, rows: Sequence[Sequence]):
        cur = self._conn.cursor()
        cur.executemany(query, [tuple(r) for r in rows])
        return cur

    def commit(self) -> None:
        self._conn.commit()

    def rollback(self) -> None:
        self._conn.rollback()

    def close(self) -> None:
        self._conn.close()


class PostgresDialect(SqlDialect):
    """Postgres over an already-installed driver (psycopg2 or pg8000).

    Activated by ``KATIB_TPU_PG_DSN``. The driver is import-gated: this
    repo never installs dependencies, so a missing driver raises a
    RuntimeError naming the knob instead of an ImportError at call depth.
    """

    name = "postgres"
    paramstyle = "format"
    history_tiebreaker = "seq"

    def __init__(self, dsn: str):
        self.dsn = dsn

    @staticmethod
    def driver():
        try:
            import psycopg2  # type: ignore

            return "psycopg2", psycopg2
        except ImportError:
            pass
        try:
            import pg8000.dbapi  # type: ignore

            return "pg8000", pg8000.dbapi
        except ImportError:
            return None, None

    def connect(self):
        name, mod = self.driver()
        if mod is None:
            raise RuntimeError(
                "KATIB_TPU_PG_DSN is set but no Postgres driver "
                "(psycopg2 or pg8000) is importable in this environment"
            )
        if name == "psycopg2":
            return _PgConnectionAdapter(mod.connect(self.dsn))
        # pg8000 takes keyword args; accept "key=value ..." DSNs
        kwargs = {}
        for part in self.dsn.split():
            k, _, v = part.partition("=")
            if k and v:
                kwargs[{"dbname": "database"}.get(k, k)] = v
        return _PgConnectionAdapter(mod.connect(**kwargs))

    def on_connect(self, conn) -> None:
        pass

    def begin(self, conn) -> None:
        # DBAPI connections open a transaction implicitly on first statement
        pass

    def schema(self) -> Sequence[str]:
        return _POSTGRES_SCHEMA

    def is_busy(self, exc: BaseException) -> bool:
        text = f"{type(exc).__name__}: {exc}".lower()
        return any(
            key in text
            for key in ("deadlock", "serialization", "lock timeout", "could not obtain lock")
        )


# -- registry ----------------------------------------------------------------

DIALECTS: Dict[str, Callable[..., SqlDialect]] = {
    "sqlite": SqliteDialect,
    "fakepg": FakePostgresDialect,
    "postgres": PostgresDialect,
}


def registered_dialects() -> List[str]:
    return sorted(DIALECTS)
