from .store import (  # noqa: F401
    BufferedObservationStore,
    InMemoryObservationStore,
    MetricLog,
    ObservationStore,
    SqliteObservationStore,
    fold_observation,
    objective_value,
    open_store,
)
from .state import ExperimentStateStore  # noqa: F401
