"""Metric reporting + collection — the TPU-native data plane.

The reference collects metrics by injecting a log-scraping sidecar into the
trial pod (pkg/webhook/v1beta1/pod/inject_webhook.go) which tails
/var/log/katib/metrics.log and reports to katib-db-manager over gRPC. On TPU
the idiomatic path is *push*: trial code calls ``report_metrics`` (the SDK
already has this push mode — sdk/python/v1beta1/kubeflow/katib/api/
report_metrics.py) and the rows land in the observation store directly.

For parity with arbitrary subprocess trials, the TEXT/JSON line parsers of the
file/stdout collector are reproduced (pkg/metricscollector/v1beta1/
file-metricscollector/file-metricscollector.go:45-120, default filter regex
from pkg/metricscollector/v1beta1/common/const.go:47).

Early-stopping rule enforcement matches the sidecar watcher
(cmd/metricscollector/v1beta1/file-metricscollector/main.go:147-386):
- each rule is deleted once it trips; the trial stops when ALL rules tripped;
- the objective metric is compared via its running optimum (max for maximize,
  min for minimize) — the medianstop workaround;
- a rule with start_step > 0 is evaluated exactly at the start_step-th report
  of its metric.
"""

from __future__ import annotations

import atexit
import contextvars
import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..api.spec import ComparisonType, EarlyStoppingRule, ObjectiveType
from ..db.store import MetricLog, ObservationStore, open_store

# reference const.go:47
DEFAULT_FILTER = r"([\w|-]+)\s*=\s*([+-]?\d*(\.\d+)?([Ee][+-]?\d+)?)"

# env keys used to rebind a subprocess trial to the store (replaces the
# sidecar + db-manager address plumbing of the reference webhook). The RPC
# URL binding (service/httpapi.py) is the out-of-process transport of the
# sharded control plane: when set it wins over the direct-SQLite path, so a
# trial on another host pushes metric streams to its owning replica over
# HTTP with retry/backoff instead of needing the db file mounted.
ENV_TRIAL_NAME = "KATIB_TPU_TRIAL_NAME"
ENV_DB_PATH = "KATIB_TPU_DB_PATH"
ENV_METRICS_FILE = "KATIB_TPU_METRICS_FILE"
ENV_RPC_URL = "KATIB_TPU_RPC_URL"
ENV_RPC_TOKEN = "KATIB_TPU_RPC_TOKEN"
# framed ingest binding (service/ingest.py): "host:port" of the owning
# replica's binary ingest plane. Wins over the RPC URL for WRITES (one
# persistent socket, struct-packed frames, server-side coalescing); reads
# still ride the JSON url. Exported by a replica running ingest_framed.
ENV_INGEST_ADDR = "KATIB_TPU_INGEST_ADDR"


class EarlyStopped(Exception):
    """Raised inside trial code when all early-stopping rules tripped."""


class TrialKilled(Exception):
    """Raised inside in-process trial code when the scheduler requested a
    kill (timeout or deleteTrials-style shrink) — the cooperative equivalent
    of the reference sidecar killing the training process."""


class TrialPreempted(Exception):
    """Raised inside in-process trial code when the fair-share policy
    (controller/fairshare.py) selected this trial as a preemption victim:
    a higher-priority gang needs the chips. Raised AFTER the report's
    metrics are persisted, so a trial that saves a checkpoint before each
    report loses nothing — the scheduler requeues it as resumable and it
    continues from its latest checkpoint when devices free up."""


class EarlyStoppingMonitor:
    """Stateful rule tracker, mirroring updateStopRules (main.go:336-386)."""

    def __init__(
        self,
        rules: Sequence[EarlyStoppingRule],
        objective_metric: str,
        objective_type: ObjectiveType,
    ):
        self.rules = list(rules)
        self.objective_metric = objective_metric
        self.objective_type = objective_type
        self.optimal_obj_value: Optional[float] = None
        self._start_step_left: Dict[str, int] = {
            r.name: r.start_step for r in rules if r.start_step != 0
        }

    @property
    def should_stop(self) -> bool:
        return not self.rules and self._had_rules

    _had_rules = False

    def observe(self, metric_name: str, value: float) -> bool:
        """Feed one metric report; returns True when the trial must stop."""
        if not self.rules:
            return self.should_stop
        self._had_rules = True
        for rule in list(self.rules):
            if rule.name != metric_name:
                continue
            self._apply_rule(rule, value)
        return not self.rules

    def _apply_rule(self, rule: EarlyStoppingRule, value: float) -> None:
        # running-optimum substitution for the objective metric
        if rule.name == self.objective_metric:
            if self.optimal_obj_value is None:
                self.optimal_obj_value = value
            elif self.objective_type == ObjectiveType.MAXIMIZE:
                self.optimal_obj_value = max(self.optimal_obj_value, value)
            elif self.objective_type == ObjectiveType.MINIMIZE:
                self.optimal_obj_value = min(self.optimal_obj_value, value)
            value = self.optimal_obj_value

        if rule.name in self._start_step_left:
            self._start_step_left[rule.name] -= 1
            if self._start_step_left[rule.name] != 0:
                return

        rule_value = float(rule.value)
        tripped = (
            (rule.comparison == ComparisonType.EQUAL and value == rule_value)
            or (rule.comparison == ComparisonType.LESS and value < rule_value)
            or (rule.comparison == ComparisonType.GREATER and value > rule_value)
        )
        if tripped:
            self.rules.remove(rule)


@dataclass
class MetricsReporter:
    """Push reporter bound to one trial; checks early-stopping on each report."""

    store: ObservationStore
    trial_name: str
    monitor: Optional[EarlyStoppingMonitor] = None
    raise_on_stop: bool = True
    kill_event: Optional[Any] = None  # threading.Event from the scheduler
    preempt_event: Optional[Any] = None  # threading.Event — fairshare preemption
    _stopped: bool = False

    def report(self, timestamp: Optional[float] = None, **metrics: float) -> None:
        fvals, logs = self.build_logs(metrics, timestamp=timestamp)
        self.store.report_observation_log(self.trial_name, logs)
        # after the write, so a killed trial's final metrics are not lost;
        # kill is checked before preempt — it is the stronger signal. The
        # flush() barrier makes buffered stores durable BEFORE the unwind:
        # PR 2's invariant that a preempted/killed trial's metrics are
        # persisted when the scheduler requeues it must survive write-behind.
        if self.kill_event is not None and self.kill_event.is_set():
            self.store.flush()
            raise TrialKilled(f"trial {self.trial_name} killed")
        if self.preempt_event is not None and self.preempt_event.is_set():
            self.store.flush()
            raise TrialPreempted(f"trial {self.trial_name} preempted")
        self.absorb(fvals)
        if self._stopped and self.raise_on_stop:
            raise EarlyStopped(f"trial {self.trial_name} early stopped")

    def build_logs(
        self, metrics: Dict[str, Any], timestamp: Optional[float] = None
    ) -> "tuple[Dict[str, float], List[MetricLog]]":
        """Validate + normalize one report into rows without writing them —
        the packed demux (runtime/packed.py) builds every member's rows via
        this and appends them in ONE store batch."""
        fvals = {k: validate_metric_value(k, v) for k, v in metrics.items()}
        ts = timestamp if timestamp is not None else time.time()
        logs = [
            MetricLog(timestamp=ts, metric_name=k, value=str(f))
            for k, f in fvals.items()
        ]
        return fvals, logs

    def absorb(self, fvals: Dict[str, float]) -> None:
        """Feed already-written values to the early-stopping monitor (no
        raise — packed mode masks instead of unwinding)."""
        if self.monitor is not None:
            for k, fv in fvals.items():
                if self.monitor.observe(k, fv):
                    self._stopped = True

    @property
    def stopped(self) -> bool:
        return self._stopped


# -- in-process trial context plumbing --------------------------------------

_current_reporter: contextvars.ContextVar[Optional[MetricsReporter]] = contextvars.ContextVar(
    "katib_tpu_reporter", default=None
)


def set_current_reporter(r: Optional[MetricsReporter]):
    return _current_reporter.set(r)


def validate_metric_value(name: str, value) -> float:
    """Normalize a pushed value to float or reject it — reference sdk
    utils.validate_metrics_value (utils.py:75-84) raises before the push
    RPC; a typo'd value must fail the trial loudly, not sail into the DB and
    surface as a Succeeded trial with an unusable objective. Returning the
    float (the stored form is str(float(v))) also keeps objects whose
    ``float()`` succeeds but whose ``str()`` is non-numeric — numpy/jax
    0-d arrays, bools, tensors — rankable once folded."""
    try:
        return float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"metric {name!r} value {value!r} is not convertible to float"
        ) from None


# One store handle per (pid, db-path) for the subprocess env binding: the
# old shape opened and closed a fresh SQLite connection on EVERY report —
# connection setup + PRAGMA + index DDL per metric row. The pid key makes a
# fork start clean (a SQLite connection must never cross fork), and atexit
# closes whatever this process opened.
_env_store_lock = threading.Lock()
_env_stores: Dict[Tuple[int, str], ObservationStore] = {}


def _close_env_stores() -> None:
    with _env_store_lock:
        stores = list(_env_stores.values())
        _env_stores.clear()
    for store in stores:
        try:
            store.close()
        except Exception:
            pass


def _env_bound_store(db_path: str) -> ObservationStore:
    # Always SQLite here: the native engine is single-writer-process and
    # the controller may hold it open; SQLite handles cross-process writes.
    key = (os.getpid(), db_path)
    with _env_store_lock:
        store = _env_stores.get(key)
        if store is None:
            if not _env_stores:
                atexit.register(_close_env_stores)
            store = open_store(db_path, backend="sqlite")
            _env_stores[key] = store
        return store


def _env_bound_rpc_store(url: str) -> ObservationStore:
    """One HTTP store per (pid, url) — same caching/atexit shape as the
    SQLite binding; the client's retry/backoff makes a restarting replica a
    stall, not a lost report."""
    from ..service.httpapi import HttpRemoteObservationStore

    key = (os.getpid(), url)
    with _env_store_lock:
        store = _env_stores.get(key)
        if store is None:
            if not _env_stores:
                atexit.register(_close_env_stores)
            store = HttpRemoteObservationStore(
                url, token=os.environ.get(ENV_RPC_TOKEN) or None
            )
            _env_stores[key] = store
        return store


def _env_bound_ingest_store(addr: str, base_url: Optional[str]) -> ObservationStore:
    """One framed store per (pid, addr): writes stream binary frames over a
    persistent socket to the replica's ingest plane; reads fall back to the
    JSON url when one is bound. Same caching/atexit shape as the other
    bindings — the pid key keeps a fork()ed child off its parent's socket."""
    from ..service.ingest import FramedObservationStore

    key = (os.getpid(), addr)
    with _env_store_lock:
        store = _env_stores.get(key)
        if store is None:
            if not _env_stores:
                atexit.register(_close_env_stores)
            store = FramedObservationStore(
                addr, base_url=base_url,
                token=os.environ.get(ENV_RPC_TOKEN) or None,
            )
            _env_stores[key] = store
        return store


def report_metrics(metrics: Optional[Dict[str, float]] = None, **kw: float) -> None:
    """SDK push entry point, reference sdk report_metrics.py:24+.

    Works in five bindings (most-specific wins):
    1. in-process trial: a contextvar reporter was installed by the runtime;
    2. subprocess trial with framed-ingest binding: streams binary frames
       over one persistent socket to the owning replica's ingest plane
       ($KATIB_TPU_INGEST_ADDR, service/ingest.py) — the hot path of the
       high-throughput ingest plane;
    3. subprocess trial with RPC binding: pushes over HTTP to the owning
       replica's DBManager ($KATIB_TPU_RPC_URL, service/httpapi.py) — the
       wire transport of the sharded control plane;
    4. subprocess trial with env binding: pushes to the cached store handle
       for $KATIB_TPU_DB_PATH (one connection per process, closed at exit);
    5. bare subprocess: prints ``name=value`` lines for the stdout collector.
    """
    merged = dict(metrics or {})
    merged.update(kw)
    r = _current_reporter.get()
    if r is not None:
        r.report(**merged)  # MetricsReporter.report validates + normalizes
        return
    trial = os.environ.get(ENV_TRIAL_NAME)
    ingest_addr = os.environ.get(ENV_INGEST_ADDR)
    rpc_url = os.environ.get(ENV_RPC_URL)
    db = os.environ.get(ENV_DB_PATH)
    if trial and (ingest_addr or rpc_url or db):
        if ingest_addr:
            store = _env_bound_ingest_store(ingest_addr, rpc_url or None)
        elif rpc_url:
            store = _env_bound_rpc_store(rpc_url)
        else:
            store = _env_bound_store(db)
        # step-stats plane (runtime/stepstats.py): a subprocess trial
        # inherits KATIB_TPU_STEP_STATS from the controller env; its perf
        # windows ride the same store binding. Empty (no clock) when unset.
        from .stepstats import env_perf_logs

        perf = env_perf_logs(trial, merged)
        if perf:
            store.report_observation_log(trial, perf)
        MetricsReporter(store=store, trial_name=trial).report(**merged)
        # rejoin the controller trace: $KATIB_TPU_TRACEPARENT (issued by the
        # subprocess executor) parents this process's report span onto the
        # trial's `execute` span (katib_tpu.tracing)
        from ..tracing import record_env_report

        record_env_report(len(merged))
        return
    for k, v in merged.items():
        # normalized so the stdout collector's numeric TEXT filter matches
        print(f"{k}={validate_metric_value(k, v)}", flush=True)


# -- pull parsers for subprocess output -------------------------------------

def parse_text_lines(
    lines: Sequence[str],
    metric_names: Sequence[str],
    filters: Optional[Sequence[str]] = None,
    base_time: Optional[float] = None,
) -> List[MetricLog]:
    """TEXT collector: regex filters with 2 capture groups (name, value);
    reference file-metricscollector.go:45-120."""
    regs = [re.compile(f) for f in (filters or [DEFAULT_FILTER])]
    wanted = set(metric_names)
    t0 = base_time if base_time is not None else time.time()
    out: List[MetricLog] = []
    for i, line in enumerate(lines):
        for reg in regs:
            for m in reg.finditer(line):
                name = m.group(1).strip()
                value = (m.group(2) or "").strip()
                if name not in wanted or value == "":
                    continue
                # monotonically increasing synthetic timestamps keep
                # 'latest' folding faithful to report order
                out.append(MetricLog(timestamp=t0 + i * 1e-6, metric_name=name, value=value))
    return out


_PROM_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+([-+0-9.eEnaifNI]+)(?:\s+\d+)?$"
)


def parse_prometheus_text(
    text: str,
    metric_names: Sequence[str],
    base_time: Optional[float] = None,
) -> List[MetricLog]:
    """Prometheus text exposition -> MetricLogs for the wanted names
    (reference CollectorKind PrometheusMetric, common_types.go:205-227;
    scraped by the subprocess executor instead of a sidecar)."""
    wanted = set(metric_names)
    t0 = base_time if base_time is not None else time.time()
    out: List[MetricLog] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE_RE.match(line)
        if m is None or m.group(1) not in wanted:
            continue
        out.append(MetricLog(timestamp=t0, metric_name=m.group(1), value=m.group(2)))
    return out


def parse_json_lines(
    lines: Sequence[str],
    metric_names: Sequence[str],
    base_time: Optional[float] = None,
) -> List[MetricLog]:
    """JSON collector: one JSON object per line; values may be str or number.
    Lines that fail to parse are skipped (subprocess logs are noisy)."""
    wanted = set(metric_names)
    t0 = base_time if base_time is not None else time.time()
    out: List[MetricLog] = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        ts = t0 + i * 1e-6
        if "timestamp" in obj:
            try:
                ts = float(obj["timestamp"])
            except (TypeError, ValueError):
                pass
        for k, v in obj.items():
            if k in wanted:
                out.append(MetricLog(timestamp=ts, metric_name=k, value=str(v)))
    return out
