"""Per-trial JAX profiler capture — xplane traces into the trial workdir.

SURVEY.md §5 designates profiler traces as the first-class TPU observability
improvement over the reference's logs+Prometheus ceiling (the reference has
no per-trial profiling at all). Trial code opts in via
``ctx.profile():`` around its hot steps; the xplane protobufs land in
``<workdir>/profile`` and are listed by the UI
(``GET /api/experiments/<e>/trials/<t>/profile``). Any TensorBoard /
xprof install can open the dump.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, List, Optional

PROFILE_DIRNAME = "profile"
ENV_PROFILE = "KATIB_TPU_PROFILE"  # stamped on trial subprocesses by the executor


def profile_enabled_from_env(default: bool = True) -> bool:
    """$KATIB_TPU_PROFILE verdict: "0"/"false"/"off" disables profiling
    fleet-wide, anything else (or unset) keeps ``default``. This is how the
    env hook is honored end-to-end: the executor stamps the controller's
    value onto trial subprocesses, and ``ctx.profile()`` (enabled=None)
    resolves through here."""
    raw = os.environ.get(ENV_PROFILE)
    if raw is None or raw == "":
        return default
    return raw.lower() not in ("0", "false", "off")


@contextlib.contextmanager
def profile_trace(
    workdir: Optional[str], enabled: Optional[bool] = None
) -> Iterator[Optional[str]]:
    """Trace JAX execution into ``<workdir>/profile``; no-op without a
    workdir or when disabled (so trial code can call it unconditionally).
    ``enabled=None`` defaults from $KATIB_TPU_PROFILE (on unless the env
    disables it — the pre-env behavior). Yields the trace directory (or
    None when inactive)."""
    if enabled is None:
        enabled = profile_enabled_from_env()
    if not workdir or not enabled:
        yield None
        return
    trace_dir = os.path.join(workdir, PROFILE_DIRNAME)
    os.makedirs(trace_dir, exist_ok=True)
    import jax

    # Guard only trace start/stop, NEVER the body: wrapping the yield in a
    # try/except would swallow EarlyStopped/TrialKilled raised inside the
    # profiled block and misclassify the trial. (Trace start can fail e.g.
    # when a second concurrent trace exists in the process.)
    trace_cm = jax.profiler.trace(trace_dir)
    try:
        trace_cm.__enter__()
    except Exception:
        trace_cm = None
    try:
        yield trace_dir
    finally:
        if trace_cm is not None:
            try:
                trace_cm.__exit__(None, None, None)
            except Exception:
                pass


def list_profile_artifacts(workdir: Optional[str]) -> List[dict]:
    """Relative paths + sizes of captured trace files under the workdir.

    Sorted directory traversal (os.walk order is filesystem-dependent) so
    the UI listing is deterministic, and tolerant of files vanishing
    between the walk and the stat (a concurrent trial cleanup)."""
    out: List[dict] = []
    if not workdir:
        return out
    root = os.path.join(workdir, PROFILE_DIRNAME)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            try:
                size = os.path.getsize(p)
            except FileNotFoundError:
                continue  # vanished between walk and stat
            out.append({"path": os.path.relpath(p, root), "bytes": size})
    return out
