"""Step clock — the per-trial half of the step-statistics plane (ISSUE 20).

The pjit/TPUv4 fleet paper (arXiv:2204.06514) treats step time and MFU as
the primary health signals of a TPU training fleet; Podracer (arXiv:
2104.06272) tunes packed schedulers off exactly this telemetry. This module
measures it from the one vantage point the runtime already owns: every
``ctx.report()`` is one step boundary. A :class:`StepClock` accumulates
per-step wall durations in a bounded ring, counts (re)compiles off JAX's
monitoring events, and flushes windowed summaries through the ordinary
observation pipeline under the reserved ``katib-tpu/perf/`` namespace —
rows the objective folder never folds (``folded`` only reads requested
metric names), so perf series can never pollute folding, warm-start
signatures, or BOHB rung models.

Everything here is inert unless the scheduler binds a clock to the trial
context (``runtime.step_stats`` / ``KATIB_TPU_STEP_STATS``): knob off means
no clock object exists and ``ctx.report`` pays one ``is None`` check.

Determinism seams (used by the durability tests — perf series for a trial
SIGKILLed mid-stint and failed over must be bit-identical to a fault-free
run):

- ``KATIB_TPU_STEP_STATS_CLOCK=counter`` replaces wall time with a counter:
  every report records exactly one 1.0 s step, so row VALUES are exact and
  replayed reports reproduce identical rows.
- ``KATIB_TPU_STEP_STATS_INJECT`` injects faults for detector tests:
  ``straggle=<member>@<factor>`` scales that pack member's recorded
  durations (GangStraggler), ``retrace=<n>`` records one synthetic
  recompile per step until n have fired (RetraceStorm). Comma-separated.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..db.store import MetricLog

# Reserved metric namespace. spec validation rejects objective/metric names
# under it, and the perf CLI / detectors read it back by this prefix.
PERF_PREFIX = "katib-tpu/perf/"

ENV_STEP_STATS = "KATIB_TPU_STEP_STATS"
ENV_CLOCK = "KATIB_TPU_STEP_STATS_CLOCK"
ENV_INJECT = "KATIB_TPU_STEP_STATS_INJECT"
ENV_FLUSH_STEPS = "KATIB_TPU_STEP_STATS_FLUSH_STEPS"

# per-step durations kept for stint percentiles (windows flush long before
# this; the ring only bounds stint-end p50/p95 memory on million-step runs)
RING_STEPS = 4096

# report kwargs the clock reads for throughput. They stay ordinary metric
# rows (the clock observes, never consumes) — knob off leaves them untouched.
VOLUME_KEYS = ("examples", "tokens")


def _truthy(v: Optional[str]) -> bool:
    return bool(v) and v.strip().lower() not in ("", "0", "false", "off")


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted sequence (deterministic,
    no interpolation — replayed series must reproduce values exactly)."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    rank = max(1, min(n, int(-(-q * n // 1))))  # ceil(q*n), clamped
    return float(sorted_vals[rank - 1])


def _parse_inject() -> Tuple[Optional[Tuple[int, float]], int]:
    spec = os.environ.get(ENV_INJECT, "") or ""
    straggle: Optional[Tuple[int, float]] = None
    retraces = 0
    for part in spec.split(","):
        part = part.strip()
        try:
            if part.startswith("straggle="):
                body = part[len("straggle="):]
                idx, _, factor = body.partition("@")
                straggle = (int(idx), float(factor) if factor else 2.0)
            elif part.startswith("retrace="):
                retraces = int(part[len("retrace="):])
        except ValueError:
            continue  # malformed injection spec: ignore, never fail a trial
    return straggle, retraces


@dataclass
class StintSummary:
    """What one ended stint measured — consumed by the controller plane's
    rollups and detectors (controller/stepstats.py)."""

    steps: int
    seconds: float
    p50: float
    p95: float
    retraces: int
    examples: float
    member_index: Optional[int] = None

    @property
    def steps_per_second(self) -> float:
        return self.steps / self.seconds if self.seconds > 0 else 0.0


class StepClock:
    """Per-trial (or per-pack-member) step timer.

    ``mark()`` is called on every ``ctx.report`` — each call records the
    wall duration since the previous one as one step. Fused population
    sweeps time whole chunks instead (``note_steps``), which switches the
    clock to external mode so demux-time reports stop double-counting.
    Completed windows (every ``flush_steps`` steps) are retrieved with
    ``drain()`` as ``(name, value)`` rows the caller writes under
    :data:`PERF_PREFIX`; ``finalize()`` closes the last partial window and
    appends the stint-level p50/p95 rows.
    """

    def __init__(
        self, flush_steps: int = 32, member_index: Optional[int] = None
    ) -> None:
        self.flush_steps = max(1, int(flush_steps))
        self.member_index = member_index
        self._counter_mode = (os.environ.get(ENV_CLOCK) or "") == "counter"
        straggle, inject_retraces = _parse_inject()
        self._factor = 1.0
        if (
            straggle is not None
            and member_index is not None
            and straggle[0] == member_index
        ):
            self._factor = straggle[1]
        self._inject_retraces_left = inject_retraces
        self._external = False
        self._last_mark: Optional[float] = None
        self._pending: List[float] = []
        self._ring: deque = deque(maxlen=RING_STEPS)
        self._windows: List[List[Tuple[str, float]]] = []
        self._compiles = 0
        self._window_retraces = 0
        self._window_volume = 0.0
        self._total_steps = 0
        self._total_seconds = 0.0
        self._total_examples = 0.0
        self._finalized = False

    # -- recording -----------------------------------------------------------

    def mark(self, metrics: Optional[Dict[str, Any]] = None) -> None:
        """One report happened. Reads (never consumes) examples/tokens for
        throughput; records one step duration unless an external timer
        (``note_steps``) owns this clock."""
        if metrics:
            for key in VOLUME_KEYS:
                v = metrics.get(key)
                if v is not None:
                    try:
                        fv = float(v)
                    except (TypeError, ValueError):
                        continue
                    self._window_volume += fv
                    self._total_examples += fv
        if self._external:
            return
        if self._counter_mode:
            self._record(1.0)
            return
        now = time.time()
        if self._last_mark is None:
            # first report closes the compile stretch — not a step
            self._last_mark = now
            return
        d = now - self._last_mark
        self._last_mark = now
        self._record(d)

    def note_steps(self, n: int, total_seconds: float) -> None:
        """External timing for fused sweeps: one compiled chunk of ``n``
        generations took ``total_seconds``. Switches the clock to external
        mode — demux-time ``mark()`` calls then only harvest volume."""
        self._external = True
        n = max(1, int(n))
        per = 1.0 if self._counter_mode else total_seconds / n
        for _ in range(n):
            self._record(per)

    def note_compile(self) -> None:
        """One backend compile finished (JAX monitoring event). Retraces are
        compiles past the first — the initial trace-and-compile is the
        expected cost, every later one is a retrace."""
        self._compiles += 1
        if self._compiles > 1:
            self._window_retraces += 1

    def _record(self, d: float) -> None:
        d *= self._factor
        if self._inject_retraces_left > 0:
            self._inject_retraces_left -= 1
            if self._compiles == 0:
                self.note_compile()  # baseline compile; retraces count past it
            self.note_compile()
        self._pending.append(d)
        self._ring.append(d)
        self._total_steps += 1
        self._total_seconds += d
        if len(self._pending) >= self.flush_steps:
            self._flush_window()

    def _flush_window(self) -> None:
        w = self._pending
        if not w:
            return
        self._pending = []
        n = len(w)
        total = sum(w)
        srt = sorted(w)
        rows: List[Tuple[str, float]] = [
            ("step_seconds_mean", total / n),
            ("step_seconds_p95", _percentile(srt, 0.95)),
        ]
        if total > 0:
            rows.append(("steps_per_second", n / total))
            if self._window_volume > 0:
                rows.append(("examples_per_second", self._window_volume / total))
        if self._window_retraces > 0:
            rows.append(("retraces", float(self._window_retraces)))
        self._window_volume = 0.0
        self._window_retraces = 0
        self._windows.append(rows)

    # -- harvesting ----------------------------------------------------------

    def drain(self) -> List[Tuple[str, float]]:
        """Completed windows' rows, flattened in flush order (names WITHOUT
        the katib-tpu/perf/ prefix — ``perf_logs`` adds it)."""
        if not self._windows:
            return []
        out: List[Tuple[str, float]] = []
        for w in self._windows:
            out.extend(w)
        self._windows = []
        return out

    @property
    def retraces(self) -> int:
        return max(0, self._compiles - 1)

    def finalize(self) -> Tuple[List[Tuple[str, float]], StintSummary]:
        """Stint ended: close the partial window, emit stint-level rows.

        Stint rows carry only duration-derived stats (p50/p95) — never raw
        counts — so a failed-over trial's replayed series stays bit-identical
        to a fault-free run (counts would differ across the resume seam)."""
        self._flush_window()
        rows = self.drain()
        durs = sorted(self._ring)
        p50 = _percentile(durs, 0.50)
        p95 = _percentile(durs, 0.95)
        if durs:
            rows.append(("stint_step_seconds_p50", p50))
            rows.append(("stint_step_seconds_p95", p95))
        self._finalized = True
        return rows, StintSummary(
            steps=self._total_steps,
            seconds=self._total_seconds,
            p50=p50,
            p95=p95,
            retraces=self.retraces,
            examples=self._total_examples,
            member_index=self.member_index,
        )


def perf_logs(
    rows: Sequence[Tuple[str, float]], timestamp: Optional[float] = None
) -> List[MetricLog]:
    """Row tuples -> MetricLogs under the reserved namespace, formatted the
    same way MetricsReporter stores values (str(float)) so perf rows ride
    every store backend and the wire planes unchanged."""
    if not rows:
        return []
    ts = timestamp if timestamp is not None else time.time()
    return [
        MetricLog(timestamp=ts, metric_name=PERF_PREFIX + name, value=str(float(v)))
        for name, v in rows
    ]


# -- JAX compile-event attribution -------------------------------------------
#
# jax.monitoring fires '/jax/core/compile/backend_compile_duration' (name
# varies by version; anything mentioning "compile" counts) on every backend
# compile. The listener registry is process-global, so attribution rides a
# contextvar set around the trial function: compiles happen synchronously in
# the executing thread, which sees its own context. For a pack there is one
# shared program — a recompile is charged to every active member's clock
# (the gang retraces together).

_active_clocks: contextvars.ContextVar[Optional[Tuple[StepClock, ...]]] = (
    contextvars.ContextVar("katib_tpu_step_clocks", default=None)
)
_listener_lock = threading.Lock()
_listener_installed = False


def _on_event_duration(event: str, duration: float, **kwargs: Any) -> None:
    if "compile" not in event:
        return
    clocks = _active_clocks.get()
    if not clocks:
        return
    for c in clocks:
        c.note_compile()


def _install_listener() -> None:
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        _listener_installed = True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_event_duration)
        except Exception:
            pass  # no jax / no monitoring API: step timing still works


def activate(clocks: Sequence[StepClock]):
    """Route this thread's compile events to ``clocks`` until the returned
    token is passed to :func:`deactivate`. Called by the trial-fn start
    hooks; the listener install is one-time and knob-gated by construction
    (no clock objects exist when step_stats is off)."""
    _install_listener()
    return _active_clocks.set(tuple(clocks))


def deactivate(token) -> None:
    try:
        _active_clocks.reset(token)
    except ValueError:
        _active_clocks.set(None)


# -- subprocess env binding ---------------------------------------------------
#
# A subprocess trial reporting via report_metrics (env/RPC/ingest store
# bindings) inherits KATIB_TPU_STEP_STATS from the controller environment;
# its perf series is produced here, one clock per (pid, trial). Series only —
# detectors and rollups live controller-side off the persisted rows.

_env_clock_lock = threading.Lock()
_env_clocks: Dict[Tuple[int, str], StepClock] = {}


def env_step_stats_enabled() -> bool:
    return _truthy(os.environ.get(ENV_STEP_STATS))


def env_perf_logs(trial: str, metrics: Dict[str, Any]) -> List[MetricLog]:
    """Mark the env-bound clock for ``trial`` and return any freshly
    completed windows as rows. Empty (and clock-free) when the knob is off."""
    if not env_step_stats_enabled():
        return []
    try:
        flush = int(os.environ.get(ENV_FLUSH_STEPS) or 32)
    except ValueError:
        flush = 32
    key = (os.getpid(), trial)
    with _env_clock_lock:
        clock = _env_clocks.get(key)
        if clock is None:
            clock = StepClock(flush_steps=flush)
            _env_clocks[key] = clock
    clock.mark(metrics)
    return perf_logs(clock.drain())


# -- offline summaries --------------------------------------------------------

def summarize_perf_rows(logs: Sequence[MetricLog]) -> Optional[Dict[str, Any]]:
    """Fold one trial's perf rows (any rows under PERF_PREFIX) into the
    summary the ``katib-tpu perf`` CLI renders. None when the trial has no
    perf series (knob was off)."""
    windows = 0
    stints = 0
    retraces = 0.0
    last: Dict[str, float] = {}
    for log in logs:
        if not log.metric_name.startswith(PERF_PREFIX):
            continue
        name = log.metric_name[len(PERF_PREFIX):]
        try:
            value = float(log.value)
        except (TypeError, ValueError):
            continue
        if name == "step_seconds_mean":
            windows += 1
        elif name == "stint_step_seconds_p50":
            stints += 1
        elif name == "retraces":
            retraces += value
        last[name] = value
    if not last:
        return None
    return {
        "windows": windows,
        "stints": stints,
        "stepSecondsP50": last.get("stint_step_seconds_p50"),
        "stepSecondsP95": last.get(
            "stint_step_seconds_p95", last.get("step_seconds_p95")
        ),
        "stepsPerSecond": last.get("steps_per_second"),
        "examplesPerSecond": last.get("examples_per_second"),
        "mfu": last.get("stint_mfu"),
        "retraces": int(retraces),
    }
