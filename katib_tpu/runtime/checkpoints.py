"""Checkpoint store — orbax-backed trial state persistence.

Replaces the reference's PVC-based checkpoint flows (SURVEY.md §5):
- PBT exploit/explore copies a parent trial's checkpoint dir
  (pbt/service.py:260-268) — here the same directory contract is used by
  katib_tpu.suggest.pbt, and this module gives trials a typed save/restore
  API on top of it;
- trial elastic resume (restart picks up the latest step).

On TPU, orbax writes sharded arrays directly from device memory per host
(OCDBT); the same API works single-host in tests. Falls back to pickle+numpy
when orbax is unavailable so the framework has no hard dependency.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..parallel.mesh import distributed_initialized as _dist_init

MAX_TO_KEEP = 3


def _pickle_steps(directory: str) -> List[int]:
    steps = []
    for f in os.listdir(directory):
        if f.startswith("ckpt_") and f.endswith(".pkl"):
            stem = f[len("ckpt_"):-len(".pkl")]
            if stem.isdigit():  # ignore foreign files like ckpt_best.pkl
                steps.append(int(stem))
    return sorted(steps)


def store_for(
    checkpoint_dir: Optional[str],
    workdir: Optional[str],
    subdir: Optional[str] = None,
    rank: int = 0,
) -> "CheckpointStore":
    """Resolve a trial's checkpoint store location — shared by
    TrialContext.checkpoint_store and the gang WorkerContext so the
    precedence rule lives in one place. ``checkpoint_dir`` (the PBT lineage
    dir when the suggester provides one) wins over the workdir. Non-primary
    gang ranks (``rank > 0``) on a SHARED checkpoint_dir get a ``rank-<i>``
    subdirectory: the pickle fallback writes fixed ``ckpt_<step>`` names, so
    concurrent ranks in one directory would truncate each other's files;
    rank 0 keeps the shared root (the lineage contract PBT's exploit copy
    reads). Per-host workdirs are already disjoint, so no suffix there."""
    base = checkpoint_dir or workdir
    if base is None:
        raise ValueError(
            "trial has no checkpoint_dir or workdir (run the experiment "
            "with a root_dir to get per-trial directories)"
        )
    if rank and checkpoint_dir is not None:
        base = os.path.join(base, f"rank-{rank}")
    if subdir:
        base = os.path.join(base, subdir)
    return CheckpointStore(base)


class CheckpointStore:
    """Save/restore a pytree (params, opt state, step...) under a directory."""

    def __init__(self, directory: str, use_orbax: Optional[bool] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401

                use_orbax = True
            except ImportError:
                use_orbax = False
            if use_orbax and _dist_init():
                # Gang workers get INDEPENDENT per-rank stores (store_for:
                # per-host workdirs / rank-<i> subdirs), but orbax's
                # CheckpointManager runs sync_global_processes barriers that
                # assume ONE checkpoint shared by every process — per-rank
                # saves then deadlock or die on a barrier-name mismatch.
                # (is_initialized() inspects only the distributed client; it
                # never initializes the XLA backend.) A future globally-
                # sharded-array checkpoint path should pass use_orbax=True
                # and a shared directory explicitly.
                use_orbax = False
        self.use_orbax = use_orbax

    # -- orbax path ----------------------------------------------------------

    def _manager(self):
        import orbax.checkpoint as ocp

        return ocp.CheckpointManager(
            self.directory, options=ocp.CheckpointManagerOptions(max_to_keep=MAX_TO_KEEP)
        )

    def save(self, step: int, state: Dict[str, Any]) -> None:
        if self.use_orbax:
            import orbax.checkpoint as ocp

            # numpy scalar leaves (np.int32(step)...) -> 0-d ndarrays: newer
            # orbax StandardSave rejects numpy scalar types outright
            state = jax.tree.map(
                lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
                state,
            )
            with self._manager() as mngr:
                mngr.save(step, args=ocp.args.StandardSave(state))
                mngr.wait_until_finished()
        else:
            host_state = jax.tree.map(np.asarray, state)
            path = os.path.join(self.directory, f"ckpt_{step}.pkl")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump({"step": step, "state": host_state}, f)
            os.replace(tmp, path)
            # same retention as the orbax path
            steps = _pickle_steps(self.directory)
            for old in steps[:-MAX_TO_KEEP]:
                try:
                    os.remove(os.path.join(self.directory, f"ckpt_{old}.pkl"))
                except OSError:
                    pass

    def latest_step(self) -> Optional[int]:
        if self.use_orbax:
            with self._manager() as mngr:
                return mngr.latest_step()
        steps = _pickle_steps(self.directory)
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None, template: Optional[Any] = None) -> Optional[Dict[str, Any]]:
        """Restore state at ``step`` (default latest); None when empty."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        if self.use_orbax:
            import orbax.checkpoint as ocp

            with self._manager() as mngr:
                if template is not None:
                    return mngr.restore(step, args=ocp.args.StandardRestore(template))
                # template-less StandardRestore: newer orbax refuses a bare
                # restore() (KeyError: no CheckpointArgs); the explicit empty
                # StandardRestore reconstructs from checkpoint metadata
                return mngr.restore(step, args=ocp.args.StandardRestore())
        path = os.path.join(self.directory, f"ckpt_{step}.pkl")
        with open(path, "rb") as f:
            return pickle.load(f)["state"]
