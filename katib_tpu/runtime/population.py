"""Fused on-device population loops — PBT and ENAS as single compiled
generation programs (ISSUE 9 / ROADMAP 2).

PR 1 vmapped K compatible trials into one program and PR 8 made sure that
program is compiled before chips are allocated — but a population-based
sweep still round-tripped suggestion → dispatch → report through the Python
controller EVERY generation, so per-generation host latency (suggestion
sync, queue walk, thread spawn, DB commit), not device math, bounded
generations/sec. Following the Anakin pattern ("Podracer architectures for
scalable Reinforcement Learning", PAPERS.md) the whole
mutate → train → evaluate → select cycle moves inside one jitted
``lax.scan`` over generations with the K-member population vmapped across
the mesh:

- a :class:`PopulationProgram` is a *pure* description of one generation:
  ``init_carry(seed)`` builds the scan carry (hyperparameters ``f32[K,P]``,
  stacked member state, scores, an ``active`` mask, a threaded
  ``jax.random`` key) and ``generation_step(carry) -> (carry, summary)``
  advances one generation. Membership masking is **traceable**: ``active``
  is a carried ``jnp`` bool array consulted inside the scan via
  ``jnp.where`` (a frozen member's state, score and hyperparameters are
  held constant and it is excluded from selection) — not a host-side numpy
  sweep;
- :func:`pbt_program` builds the PBT step — truncation-quantile
  segmentation exactly mirroring ``suggest/pbt.py`` (bottom
  ``truncation_threshold`` fraction exploits, the rest explores), exploit
  as a ``jnp.take``/``jnp.where`` gather of a random upper-quantile
  member's hyperparameters AND state, explore as the ×0.8/×1.2
  perturbation (or grid resample with ``resample_probability``), all
  driven by the threaded key;
- :func:`enas_program` builds the ENAS step — the controller LSTM
  (``suggest/nas/enas._sample_and_score``) samples K architectures, a
  weight-shared child supernet trains and evaluates them, and a REINFORCE
  loop updates the controller, all inside the scan body;
- only per-generation summaries ({score[K], best, median, lineage}) leave
  the device: they accumulate in the scan output and are demuxed into the
  PR 3 obslog after the chunk returns. An optional ``io_callback`` stream
  (``runtime.population_stream_telemetry``) surfaces {generation, best,
  median} live — both for ``katib-tpu top`` visibility and as the watchdog
  heartbeat during chunks longer than ``runtime.stall_seconds``;
- the scan runs in chunks of ``runtime.population_chunk_generations`` so
  the PR 2 cooperative-preemption invariant holds at chunk granularity:
  the carry (including the PRNG key) is checkpointed atomically at every
  chunk boundary, metrics are persisted before a preempted sweep requeues,
  and a resumed sweep continues the exact key stream — bit-identical to an
  uninterrupted run.

Trial templates opt in via ``fn.population_program(spec) ->
PopulationProgram`` (the fused analogue of PR 7's ``fn.abstract_program``)
plus an explicit spec opt-in (algorithm setting ``fused`` / ``fused_-
generations``); ``runtime.fused_population=false`` or
``KATIB_TPU_FUSED_POPULATION=0`` restores the legacy per-generation
job-queue driver byte-identically.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("katib_tpu.population")

# Label stamped on every member trial of a fused sweep (value = member
# index); its presence is how the scheduler routes the formed pack to the
# FusedPopulationExecutor instead of the PackedTrialExecutor.
FUSED_LABEL = "fusedpop.katib-tpu/member"

# Sweep-carry checkpoint files inside the sweep's checkpoint directory.
CARRY_FILE = "population_carry.npz"
CARRY_META_FILE = "population_carry.json"

# Algorithm settings recognized by the fused driver (spec-side opt-in).
SETTING_FUSED = "fused"
SETTING_GENERATIONS = "fused_generations"
SETTING_POPULATION = "n_population"

_TRUTHY = ("1", "true", "on", "yes")


# ---------------------------------------------------------------------------
# Program description
# ---------------------------------------------------------------------------

@dataclass
class PopulationProgram:
    """One population workload, described as pure jittable functions.

    ``init_carry(seed)`` returns the scan carry: a pytree of concrete jnp
    arrays that MUST contain ``active`` (bool[K]), ``key`` (PRNG key) and
    ``generation`` (int32 scalar). ``generation_step(carry)`` returns
    ``(carry', summary)`` where ``summary`` holds at least ``score``
    (f32[K], the raw objective value each member achieved this generation),
    ``best`` and ``median`` (f32 scalars, already in objective units).
    Everything else in the summary (lineage, architectures, perturb
    factors) is program-specific and rides along to the tests/bench."""

    name: str                           # target label ("module:fn style")
    metric: str                         # objective metric name for the obslog
    n_population: int                   # K
    init_carry: Callable[[int], Any]
    generation_step: Callable[[Any], Tuple[Any, Dict[str, Any]]]
    hyperparam_names: List[str] = field(default_factory=list)
    # per-member initial parameter assignments ({name: str-value}) used to
    # label the K member trials; values must parse as floats (packability)
    initial_assignments: Optional[Callable[[int], List[Dict[str, str]]]] = None
    seed: int = 0


# ---------------------------------------------------------------------------
# Masked statistics (selection must see only ACTIVE members)
# ---------------------------------------------------------------------------

def masked_quantile(values, mask, q):
    """``np.quantile(values[mask], q)`` (linear interpolation), traceable:
    inactive entries sort to +inf and the interpolation index is computed
    from the active count. Meaningless when no member is active — the
    drivers stop the scan before that can happen."""
    import jax.numpy as jnp

    k = values.shape[0]
    s = jnp.sort(jnp.where(mask, values, jnp.inf))
    n = jnp.sum(mask)
    pos = q * jnp.maximum(n - 1, 0).astype(jnp.float32)
    lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, k - 1)
    hi = jnp.clip(jnp.ceil(pos).astype(jnp.int32), 0, k - 1)
    frac = pos - lo.astype(jnp.float32)
    return s[lo] * (1.0 - frac) + s[hi] * frac


def masked_median(values, mask):
    return masked_quantile(values, mask, 0.5)


def masked_best(values, mask, goal_scale):
    """Best raw objective over active members (max for goal_scale=+1, min
    for -1)."""
    import jax.numpy as jnp

    scaled = jnp.where(mask, values * goal_scale, -jnp.inf)
    return values[jnp.argmax(scaled)]


# ---------------------------------------------------------------------------
# PBT: truncation selection + explore/exploit as one traced step
# ---------------------------------------------------------------------------

def pbt_program(
    *,
    name: str,
    metric: str,
    n_population: int,
    hyperparams: List[str],
    lower,
    upper,
    grid_step=None,
    truncation: float = 0.2,
    resample_probability: Optional[float] = None,
    goal_scale: float = 1.0,
    init_member: Callable[[Any, Any], Any] = None,
    member_step: Callable[[Any, Any, Any], Tuple[Any, Any]] = None,
    seed: int = 0,
    stream: Optional[Callable[[Any, Any, Any], None]] = None,
) -> PopulationProgram:
    """Build the generic fused PBT program.

    ``init_member(key, hp_row) -> state`` and ``member_step(state, hp_row,
    key) -> (state, raw_score)`` describe ONE member; both are vmapped
    across the K-member population. ``lower``/``upper``/``grid_step`` are
    per-hyperparameter bounds ([P] float arrays; ``grid_step[j] > 0``
    quantizes seeding/resampling to the ``suggest/pbt.py`` sample grid).
    Selection mirrors the job-queue suggester: members below the
    ``truncation`` quantile of the (goal-scaled) score exploit a uniformly
    random member at or above the ``1 - truncation`` quantile — copying its
    hyperparameters AND its training state — while every other active
    member explores by perturbing each hyperparameter ×0.8/×1.2 (clipped to
    bounds), or, when ``resample_probability`` is set, by resampling each
    hyperparameter from the grid with that probability and keeping it
    otherwise. Frozen (inactive) members take no part: their state,
    score and hyperparameters are held constant via ``jnp.where`` and they
    are masked out of the quantiles and the replacement pool."""
    import jax
    import jax.numpy as jnp

    k = int(n_population)
    p = len(hyperparams)
    lo_b = jnp.asarray(np.asarray(lower, dtype=np.float32).reshape(p))
    hi_b = jnp.asarray(np.asarray(upper, dtype=np.float32).reshape(p))
    steps = np.asarray(
        grid_step if grid_step is not None else np.zeros((p,)), dtype=np.float32
    ).reshape(p)
    # grid sizes are static program constants (the suggest/pbt.py sample
    # lists): n_vals[j] points from lower with spacing grid_step[j]
    n_vals = np.where(
        steps > 0,
        np.floor((np.asarray(upper) - np.asarray(lower)) / np.where(steps > 0, steps, 1.0) + 1e-9) + 1,
        0,
    ).astype(np.int32)
    n_vals_j = jnp.asarray(n_vals)
    steps_j = jnp.asarray(steps)
    tt = float(truncation)
    scale = float(goal_scale)

    def _grid_sample(key):
        """One [K, P] draw from the quantized sample grid (continuous
        uniform where no grid step is configured)."""
        k_grid, k_cont = jax.random.split(key)
        idx = jax.random.randint(
            k_grid, (k, p), 0, jnp.maximum(n_vals_j, 1)[None, :]
        )
        gridded = lo_b[None, :] + idx.astype(jnp.float32) * steps_j[None, :]
        cont = jax.random.uniform(
            k_cont, (k, p), minval=lo_b[None, :], maxval=hi_b[None, :]
        )
        return jnp.where(n_vals_j[None, :] > 0, gridded, cont)

    def init_carry(seed_val: int):
        key = jax.random.PRNGKey(int(seed_val))
        key, k_hp, k_init = jax.random.split(key, 3)
        hp = _grid_sample(k_hp)
        state = jax.vmap(init_member)(jax.random.split(k_init, k), hp)
        return {
            "hparams": hp,
            "state": state,
            "score": jnp.zeros((k,), jnp.float32),
            "active": jnp.ones((k,), bool),
            "key": key,
            "generation": jnp.asarray(0, jnp.int32),
        }

    def generation_step(carry):
        active = carry["active"]
        key, k_train, k_choice, k_factor, k_rs_gate, k_rs = jax.random.split(
            carry["key"], 6
        )

        # -- train + evaluate one generation (vmapped, mask-frozen) ---------
        new_state, raw = jax.vmap(member_step)(
            carry["state"], carry["hparams"], jax.random.split(k_train, k)
        )
        exp_mask = lambda m, leaf: m.reshape((k,) + (1,) * (leaf.ndim - 1))
        state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(exp_mask(active, n), n, o),
            new_state, carry["state"],
        )
        score = jnp.where(active, raw, carry["score"])

        # -- truncation segmentation (suggest/pbt.py _segment) --------------
        scaled = score * scale
        q_lo = masked_quantile(scaled, active, tt)
        q_hi = masked_quantile(scaled, active, 1.0 - tt)
        exploit = active & (scaled < q_lo)
        upper_pool = active & (scaled >= q_hi)
        explore = active & ~exploit
        # replacement pool fallback mirrors _generate: upper, else explore,
        # else exploit survivors (degenerate all-equal populations)
        pool = jnp.where(
            jnp.any(upper_pool), upper_pool,
            jnp.where(jnp.any(explore), explore, active),
        )
        logits = jnp.where(pool, 0.0, -jnp.inf)
        replacement = jax.random.categorical(k_choice, logits, shape=(k,))
        parent = jnp.where(exploit, replacement, jnp.arange(k))

        # -- exploit: gather the replacement's hyperparams AND state --------
        next_hp = jnp.take(carry["hparams"], parent, axis=0)
        next_state = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, parent, axis=0), state
        )
        next_score = jnp.take(score, parent)

        # -- explore: perturb ×0.8/×1.2 or grid-resample --------------------
        factors = jnp.where(
            jax.random.bernoulli(k_factor, 0.5, (k, p)), 1.2, 0.8
        )
        perturbed = jnp.clip(next_hp * factors, lo_b[None, :], hi_b[None, :])
        if resample_probability is not None:
            gate = jax.random.bernoulli(
                k_rs_gate, float(resample_probability), (k, p)
            )
            explored_hp = jnp.where(gate, _grid_sample(k_rs), next_hp)
            applied_factors = jnp.where(gate, 0.0, 1.0)
        else:
            explored_hp = perturbed
            applied_factors = factors
        explore_col = explore[:, None]
        next_hp = jnp.where(explore_col, explored_hp, next_hp)
        lineage_factors = jnp.where(explore_col, applied_factors, 1.0)

        # -- freeze: inactive members keep everything -----------------------
        next_hp = jnp.where(active[:, None], next_hp, carry["hparams"])
        next_state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(exp_mask(active, n), n, o),
            next_state, state,
        )
        next_score = jnp.where(active, next_score, score)

        best = masked_best(score, active, scale)
        median = masked_median(score * scale, active) * scale
        generation = carry["generation"]
        if stream is not None:
            _emit_stream(stream, generation, best, median)
        summary = {
            "score": score,
            "best": best,
            "median": median,
            "hparams": carry["hparams"],
            "parent": jnp.where(exploit, parent, -1).astype(jnp.int32),
            "exploited": exploit,
            "factors": lineage_factors,
            "active": active,
        }
        next_carry = {
            "hparams": next_hp,
            "state": next_state,
            "score": next_score,
            "active": active,
            "key": key,
            "generation": generation + 1,
        }
        return next_carry, summary

    def initial_assignments(seed_val: int) -> List[Dict[str, str]]:
        hp = np.asarray(init_carry(seed_val)["hparams"])
        return [
            {hyperparams[j]: repr(float(hp[i, j])) for j in range(p)}
            for i in range(k)
        ]

    return PopulationProgram(
        name=name,
        metric=metric,
        n_population=k,
        init_carry=init_carry,
        generation_step=generation_step,
        hyperparam_names=list(hyperparams),
        initial_assignments=initial_assignments,
        seed=int(seed),
    )


# ---------------------------------------------------------------------------
# ENAS: controller-LSTM sample → shared-child train/eval → REINFORCE update
# ---------------------------------------------------------------------------

def enas_program(
    *,
    name: str,
    metric: str,
    n_population: int,
    num_layers: int,
    num_ops: int,
    child_init: Callable[[Any], Any],
    child_train_eval: Callable[[Any, Any, Any, Any], Tuple[Any, Any]],
    hidden_size: int = 64,
    temperature: Optional[float] = 5.0,
    tanh_const: Optional[float] = 2.25,
    entropy_weight: Optional[float] = 1e-5,
    baseline_decay: float = 0.999,
    learning_rate: float = 5e-5,
    skip_target: float = 0.4,
    skip_weight: Optional[float] = 0.8,
    controller_steps: int = 10,
    goal_scale: float = 1.0,
    seed: int = 0,
    stream: Optional[Callable[[Any, Any, Any], None]] = None,
) -> PopulationProgram:
    """Build the fused ENAS program: one generation = sample K
    architectures with the controller LSTM (the exact
    ``suggest/nas/enas._sample_and_score`` rollout, vmapped over K keys),
    train the weight-shared child on them and evaluate each
    (``child_train_eval(child_state, arcs, key, active) -> (child_state,
    acc[K])``), then run ``controller_steps`` REINFORCE updates with
    reward = masked mean child metric — the whole cycle inside the scan
    body, so G generations are ONE compiled program instead of G
    suggestion-service round-trips."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..suggest.nas.enas import _init_params, _sample_and_score

    k = int(n_population)
    tx = optax.adam(float(learning_rate))
    scale = float(goal_scale)

    def sample_one(params, key):
        return _sample_and_score(
            params, key, int(num_layers), temperature, tanh_const,
            float(skip_target),
        )

    def init_carry(seed_val: int):
        key = jax.random.PRNGKey(int(seed_val))
        key, k_ctrl, k_child = jax.random.split(key, 3)
        params = _init_params(k_ctrl, int(num_ops), int(hidden_size))
        return {
            "ctrl": params,
            "opt": tx.init(params),
            "baseline": jnp.asarray(0.0, jnp.float32),
            "child": child_init(k_child),
            "score": jnp.zeros((k,), jnp.float32),
            "active": jnp.ones((k,), bool),
            "key": key,
            "generation": jnp.asarray(0, jnp.int32),
        }

    def generation_step(carry):
        active = carry["active"]
        key, k_sample, k_child, k_train = jax.random.split(carry["key"], 4)

        # -- controller rollout: K architectures from the LSTM sampler ------
        arcs, *_ = jax.vmap(lambda kk: sample_one(carry["ctrl"], kk))(
            jax.random.split(k_sample, k)
        )
        arcs = arcs.astype(jnp.int32)

        # -- weight-shared child: train on + evaluate the K archs -----------
        child_state, raw = child_train_eval(carry["child"], arcs, k_child, active)
        score = jnp.where(active, raw, carry["score"])
        reward_base = (
            jnp.sum(jnp.where(active, score, 0.0))
            / jnp.maximum(jnp.sum(active), 1)
        ) * scale

        # -- REINFORCE controller update (enas._train_controller, traced) ---
        def ctrl_step(_, st):
            params, opt_state, baseline, kk = st
            kk, sub = jax.random.split(kk)

            def loss_fn(p):
                _, log_prob, entropy, skip_penalty, _ = sample_one(p, sub)
                reward = reward_base
                if entropy_weight is not None:
                    reward = reward + float(entropy_weight) * entropy
                new_baseline = baseline - (1.0 - float(baseline_decay)) * (
                    baseline - reward
                )
                loss = log_prob * (reward - new_baseline)
                if skip_weight is not None:
                    loss = loss + float(skip_weight) * skip_penalty
                return loss, new_baseline

            (_, new_baseline), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, new_baseline, kk)

        params, opt_state, baseline, _ = jax.lax.fori_loop(
            0, int(controller_steps), ctrl_step,
            (carry["ctrl"], carry["opt"], carry["baseline"], k_train),
        )

        best = masked_best(score, active, scale)
        median = masked_median(score * scale, active) * scale
        generation = carry["generation"]
        if stream is not None:
            _emit_stream(stream, generation, best, median)
        summary = {
            "score": score,
            "best": best,
            "median": median,
            "arc": arcs,
            "active": active,
        }
        next_carry = {
            "ctrl": params,
            "opt": opt_state,
            "baseline": baseline,
            "child": child_state,
            "score": score,
            "active": active,
            "key": key,
            "generation": generation + 1,
        }
        return next_carry, summary

    def initial_assignments(_seed_val: int) -> List[Dict[str, str]]:
        # architectures are sampled inside the program; member trials are
        # labeled by population slot only
        return [{"member": str(i)} for i in range(k)]

    return PopulationProgram(
        name=name,
        metric=metric,
        n_population=k,
        init_carry=init_carry,
        generation_step=generation_step,
        hyperparam_names=["member"],
        initial_assignments=initial_assignments,
        seed=int(seed),
    )


def _emit_stream(sink, generation, best, median) -> None:
    """Per-generation host stream from inside the scan body (io_callback):
    ordered so the live view advances monotonically. Degrades to a no-op on
    jax builds without io_callback."""
    try:
        from jax.experimental import io_callback
    except ImportError:  # pragma: no cover - old jax
        return
    io_callback(sink, None, generation, best, median, ordered=True)


# ---------------------------------------------------------------------------
# Live stream registry (the katib-tpu top hook)
# ---------------------------------------------------------------------------

_LIVE_LOCK = threading.Lock()
_LIVE: Dict[str, Dict[str, float]] = {}


def stream_sink(experiment: str, heartbeat: Optional[Callable[[], None]] = None):
    """Host-side sink for the in-scan io_callback stream: records the
    latest {generation, best, median} under the experiment name (surfaced
    by :func:`live_status`) and fires the telemetry heartbeat so a chunk
    longer than ``runtime.stall_seconds`` cannot trip the PR 5 watchdog."""

    def sink(generation, best, median):
        with _LIVE_LOCK:
            _LIVE[experiment] = {
                "generation": int(generation),
                "best": float(best),
                "median": float(median),
            }
        if heartbeat is not None:
            heartbeat()

    return sink


def live_status(experiment: Optional[str] = None) -> Dict[str, Any]:
    """Latest streamed per-generation summary (all experiments, or one)."""
    with _LIVE_LOCK:
        if experiment is not None:
            return dict(_LIVE.get(experiment, {}))
        return {k: dict(v) for k, v in _LIVE.items()}


def clear_live_status() -> None:
    with _LIVE_LOCK:
        _LIVE.clear()


# ---------------------------------------------------------------------------
# Chunked drivers (fused = one compiled scan; legacy = chunk of 1)
# ---------------------------------------------------------------------------

def build_chunk_fn(
    program: PopulationProgram,
    length: int,
    stream: Optional[Callable[[Any, Any, Any], None]] = None,
):
    """The fused chunk program: ``carry -> (carry, ys)`` scanning
    ``generation_step`` over ``length`` generations, optionally emitting
    the per-generation {generation, best, median} io_callback stream.
    Callers jit (or AOT compile) the returned function ONCE and reuse it
    for every equal-length chunk — creating it inside a chunk loop would
    re-trace per chunk, the exact KTC101/KTC105 hazard the analyzer exists
    to catch."""
    import jax

    def body(carry, _):
        next_carry, summary = program.generation_step(carry)
        if stream is not None:
            _emit_stream(
                stream, carry["generation"], summary["best"], summary["median"]
            )
        return next_carry, summary

    def chunk(carry):
        return jax.lax.scan(body, carry, None, length=int(length))

    return chunk


def chunk_lengths(span: int, chunk: int) -> List[int]:
    """The distinct scan lengths a chunked drive of ``span`` generations
    uses: the chunk body and, when it does not divide evenly, the tail
    remainder — at most two compiled programs per sweep."""
    span, chunk = int(span), max(1, int(chunk))
    if span <= 0:
        return []
    if span <= chunk:
        return [span]
    rem = span % chunk
    return [chunk] if rem == 0 else [chunk, rem]


def run_generations(
    program: PopulationProgram,
    generations: int,
    chunk: Optional[int] = None,
    seed: Optional[int] = None,
    on_chunk: Optional[Callable[[Any, Dict[str, np.ndarray], int], Any]] = None,
    carry: Any = None,
    start_generation: int = 0,
) -> Tuple[Any, Dict[str, np.ndarray]]:
    """Drive ``generations`` generations of ``program`` in compiled chunks.

    ``chunk=None`` (or >= generations) is the fully fused mode: ONE
    compiled ``lax.scan`` program executes the whole sweep. ``chunk=1``
    models the per-generation job-queue driver: one compiled call plus a
    host round-trip per generation — the comparison driver for the
    fused-vs-legacy equivalence tests and the throughput bench. Both modes
    run the identical step function on the identical carry, so their
    lineage and metrics match bit-for-bit under a fixed seed.

    ``on_chunk(carry, ys, generation_done)`` runs at every chunk boundary
    (checkpointing, demux, preemption checks); it may return a replacement
    carry (e.g. with the ``active`` mask ANDed against host-side kill
    state) or None to keep the current one. Returns the final carry and
    the stacked per-generation summaries as numpy arrays."""
    import jax

    if carry is None:
        carry = program.init_carry(program.seed if seed is None else seed)
    total = int(generations)
    chunk = total if chunk is None else max(1, min(int(chunk), max(total, 1)))
    collected: List[Dict[str, np.ndarray]] = []
    done = int(start_generation)
    # one jitted callable per distinct chunk length (at most two: the body
    # length and the tail remainder), built BEFORE the loop — jax.jit is
    # lazy, so unused lengths never trace
    jitted = {
        length: jax.jit(build_chunk_fn(program, length))
        for length in chunk_lengths(total - done, chunk)
    }
    while done < total:
        length = min(chunk, total - done)
        fn = jitted[length]
        carry, ys = fn(carry)
        ys_np = {k2: np.asarray(v) for k2, v in ys.items()}
        collected.append(ys_np)
        done += length
        if on_chunk is not None:
            replacement = on_chunk(carry, ys_np, done)
            if replacement is not None:
                carry = replacement
    if not collected:
        return carry, {}
    stacked = {
        k2: np.concatenate([c[k2] for c in collected], axis=0)
        for k2 in collected[0]
    }
    return carry, stacked


# ---------------------------------------------------------------------------
# Sweep-carry checkpointing (chunk-granularity preemption/resume)
# ---------------------------------------------------------------------------

def save_sweep_checkpoint(
    directory: str,
    carry: Any,
    generation_done: int,
    pending_ys: Optional[Dict[str, np.ndarray]] = None,
    reported: int = 0,
) -> None:
    """Atomically persist the sweep state at a chunk boundary: the carry
    pytree (flattened; including the PRNG key, so resume continues the
    exact stream), how many generations have completed on-device, the
    not-yet-demuxed summaries of the interrupted chunk and how many of its
    generations already reached the obslog. tmp + ``os.replace`` — a crash
    mid-write leaves the previous checkpoint intact.

    The meta rides INSIDE the npz (``__meta__``) so carry+meta commit in
    ONE replace: a SIGKILL between two separate file replaces used to
    leave a torn pair (new carry arrays, stale generation counter) and the
    resumed sweep double-reported the stale tail. The json file is still
    written afterwards, but purely as a mirror for watchers/humans —
    loads treat the embedded copy as authoritative."""
    import jax

    os.makedirs(directory, exist_ok=True)
    leaves = jax.tree_util.tree_leaves(carry)
    arrays = {f"c{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    if pending_ys:
        for k2, v in pending_ys.items():
            arrays[f"y_{k2}"] = np.asarray(v)
    meta = {
        "generationDone": int(generation_done),
        "reported": int(reported),
        "pendingKeys": sorted(pending_ys) if pending_ys else [],
        "leaves": len(leaves),
    }
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8
    )
    # staging names are dot-prefixed so recovery's checkpoint-instant scan
    # (latest_checkpoint_time matches population_carry*) can never mistake
    # a torn half-written tmp for a durable carry — a SIGKILL mid-savez
    # used to leave a too-new tmp that silently disabled tail truncation
    path = os.path.join(directory, CARRY_FILE)
    tmp = os.path.join(directory, "." + CARRY_FILE + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)
    mpath = os.path.join(directory, CARRY_META_FILE)
    mtmp = os.path.join(directory, "." + CARRY_META_FILE + ".tmp")
    with open(mtmp, "w") as f:
        json.dump(meta, f)
    os.replace(mtmp, mpath)


def load_sweep_checkpoint(directory: Optional[str], program: PopulationProgram):
    """Restore a persisted sweep state, or None (no checkpoint / unreadable
    — a corrupt checkpoint falls back to a fresh sweep, loudly). Returns
    ``(carry, generation_done, pending_ys, reported)``."""
    import jax

    if not directory:
        return None
    path = os.path.join(directory, CARRY_FILE)
    mpath = os.path.join(directory, CARRY_META_FILE)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as data:
            if "__meta__" in data.files:
                meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
            else:
                # pre-embedded-meta checkpoint: the sidecar json is all there is
                with open(mpath) as f:
                    meta = json.load(f)
            template = program.init_carry(program.seed)
            t_leaves, treedef = jax.tree_util.tree_flatten(template)
            if meta.get("leaves") != len(t_leaves):
                raise ValueError("carry structure changed")
            import jax.numpy as jnp

            leaves = [
                jnp.asarray(data[f"c{i}"], dtype=t_leaves[i].dtype)
                for i in range(len(t_leaves))
            ]
            carry = jax.tree_util.tree_unflatten(treedef, leaves)
            pending = {
                k2: np.asarray(data[f"y_{k2}"]) for k2 in meta.get("pendingKeys", [])
            }
        return carry, int(meta["generationDone"]), pending, int(meta["reported"])
    except Exception as e:
        log.warning(
            "corrupt population checkpoint under %s (%s: %s); sweep restarts "
            "from scratch", directory, type(e).__name__, e,
        )
        return None


def clear_sweep_checkpoint(directory: Optional[str]) -> None:
    if not directory:
        return
    for name in (CARRY_FILE, CARRY_META_FILE):
        try:
            os.unlink(os.path.join(directory, name))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Spec-side applicability (the controller consults these)
# ---------------------------------------------------------------------------

_ENABLED: Optional[bool] = None  # None = resolve from the environment


def set_enabled(enabled: bool) -> None:
    """Config hook (runtime.fused_population): ExperimentController calls
    this at construction so every consumer — pack capacity, executor
    selection, the fused reconcile branch — sees one switch (the same
    pattern as analysis.program.set_enabled)."""
    global _ENABLED
    _ENABLED = bool(enabled)


def runtime_enabled() -> bool:
    if _ENABLED is not None:
        return _ENABLED
    return os.environ.get("KATIB_TPU_FUSED_POPULATION", "1").lower() not in (
        "0", "false", "off",
    )


def spec_opted_in(spec) -> bool:
    """The experiment asked for the fused driver: algorithm setting
    ``fused`` truthy or an explicit ``fused_generations``. Opt-in is
    per-spec so every existing PBT/ENAS experiment keeps the job-queue
    path byte-identically."""
    settings = spec.algorithm.settings_dict()
    if settings.get(SETTING_FUSED, "").lower() in _TRUTHY:
        return True
    return SETTING_GENERATIONS in settings


def fused_applicable(spec) -> Optional[str]:
    """None when this spec can dispatch as one fused sweep, else the
    human-readable reason it falls back to the job-queue driver."""
    template = spec.trial_template
    if not runtime_enabled():
        return "fused population runtime disabled (runtime.fused_population)"
    if not spec_opted_in(spec):
        return "spec did not opt in (algorithm setting fused/fused_generations)"
    if template.command is not None:
        return "command templates run as subprocesses"
    if template.resources.num_hosts > 1:
        return "multi-host trials form their own gang"
    fn = _resolved_function(template)
    if fn is None:
        return "trial function cannot be resolved"
    if getattr(fn, "population_program", None) is None:
        return "trial function exposes no population_program probe"
    return None


def _resolved_function(template):
    if getattr(template, "command", None) is not None:
        return None
    if getattr(template, "function", None) is not None:
        return template.function
    if getattr(template, "entry_point", None):
        try:
            from ..controller.executor import resolve_entry_point

            return resolve_entry_point(template)
        except Exception:
            return None
    return None


def build_program(spec) -> PopulationProgram:
    """The spec's fused program (the template must be applicable)."""
    fn = _resolved_function(spec.trial_template)
    return fn.population_program(spec)


def generation_count(spec, program: Optional[PopulationProgram] = None) -> int:
    """G for one sweep: the explicit ``fused_generations`` setting, else
    derived from the legacy budget — ``max_trial_count`` trials at K per
    generation is ``max_trial_count // K`` generations."""
    settings = spec.algorithm.settings_dict()
    if SETTING_GENERATIONS in settings:
        return max(1, int(settings[SETTING_GENERATIONS]))
    k = program.n_population if program is not None else int(
        settings.get(SETTING_POPULATION, "8")
    )
    if spec.max_trial_count:
        return max(1, int(spec.max_trial_count) // max(k, 1))
    return 1


def member_name(spec, index: int) -> str:
    """Deterministic member-trial name — resume after a controller restart
    re-derives the same names."""
    return f"{spec.name}-fused-m{index:02d}"


def fused_group_key(spec, chunk_length: int):
    """Compile-service registry key for the fused chunk program: template
    digest + population size + scan length — the fused analogue of the PR 7
    dispatch-group key, so the sweep's executable is fingerprinted,
    prewarmed and deduplicated like any dispatch group."""
    from ..analysis import program as semantic

    digest = semantic.template_digest(spec.trial_template)
    settings = spec.algorithm.settings_dict()
    return (
        "fusedpop",
        digest,
        settings.get(SETTING_POPULATION, ""),
        int(chunk_length),
    )


def fused_probe(spec, chunk_length: int, program: Optional[PopulationProgram] = None):
    """ProgramProbe describing the fused chunk program abstractly (carry
    avals via ``jax.eval_shape`` over ``init_carry``) — what the PR 8
    compile service AOT-traces and compiles at admission. The executable it
    produces is called with the concrete carry, so a warm sweep starts
    with zero inline compilation."""
    import jax

    from ..analysis.program import ProgramProbe

    program = program or build_program(spec)
    template_carry = program.init_carry(program.seed)
    avals = jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype), template_carry
    )
    return ProgramProbe(
        fn=build_chunk_fn(program, chunk_length),
        args=(avals,),
        statics={
            "fused": "population",
            "K": program.n_population,
            "chunk": int(chunk_length),
        },
    )


def prewarm_fused(compile_service, spec, chunk_generations: int) -> Optional[Any]:
    """Admission-time AOT prewarm of the fused chunk program through the
    PR 8 compile service — fingerprinted, cost-ordered and cached exactly
    like a per-trial dispatch group. Best-effort: any failure leaves the
    sweep on the inline-jit path."""
    if compile_service is None or fused_applicable(spec) is not None:
        return None
    try:
        program = build_program(spec)
        total = generation_count(spec, program)
        chunk = min(max(1, int(chunk_generations or total)), total)
        key = fused_group_key(spec, chunk)
        return compile_service.request_group(
            key,
            experiment=spec.name,
            target=f"fusedpop:{program.name}",
            builder=lambda _assignments, _spec=spec, _chunk=chunk, _p=program: (
                fused_probe(_spec, _chunk, _p)
            ),
        )
    except Exception:
        log.debug("fused population prewarm failed", exc_info=True)
        return None
