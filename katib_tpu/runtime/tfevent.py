"""TFEvent metrics collector — scalar extraction from tfevent files.

reference pkg/metricscollector/v1beta1/tfevent-metricscollector/
tfevent_loader.py:45-114 (TFEventFileParser walks the event dir with
TensorBoard's EventAccumulator and reports named scalars as observation
logs). This environment ships no TensorFlow/TensorBoard, so the TFRecord
framing and the Event/Summary protobuf wire format are decoded directly:

- TFRecord frame: u64 length, u32 masked-crc(length), payload,
  u32 masked-crc(payload)  (CRCs are not verified — tolerant reader);
- Event proto: wall_time=1 (double), step=2 (int64), summary=5 (message);
- Summary.Value: tag=1 (string), simple_value=2 (float, TF1) or
  tensor=8 with float content (TF2 scalar summaries).

Metric naming matches the reference: a metric named "accuracy" matches tags
"accuracy" and "<anything>/accuracy" (tfevent_loader.py parse_summary).
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..db.store import MetricLog

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_LEN = 2
_WIRE_32BIT = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield (field_number, wire_type, raw_value) over a message buffer."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == _WIRE_VARINT:
            v, pos = _read_varint(buf, pos)
            yield field, wire, v
        elif wire == _WIRE_64BIT:
            yield field, wire, buf[pos : pos + 8]
            pos += 8
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos : pos + ln]
            pos += ln
        elif wire == _WIRE_32BIT:
            yield field, wire, buf[pos : pos + 4]
            pos += 4
        else:
            return  # unknown wire type: stop parsing this message


def _parse_tensor_scalar(buf: bytes) -> Optional[float]:
    """TensorProto: float_val=5 (packed/repeated float), double_val=6,
    tensor_content=4 (raw bytes), dtype=1."""
    dtype = None
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == _WIRE_VARINT:
            dtype = val
        elif field == 5:
            if wire == _WIRE_32BIT:
                return struct.unpack("<f", val)[0]
            if wire == _WIRE_LEN and len(val) >= 4:
                return struct.unpack("<f", val[:4])[0]
        elif field == 6:
            if wire == _WIRE_64BIT:
                return struct.unpack("<d", val)[0]
            if wire == _WIRE_LEN and len(val) >= 8:
                return struct.unpack("<d", val[:8])[0]
        elif field == 4 and wire == _WIRE_LEN and val:
            if dtype in (None, 1) and len(val) >= 4:  # DT_FLOAT
                return struct.unpack("<f", val[:4])[0]
            if dtype == 2 and len(val) >= 8:  # DT_DOUBLE
                return struct.unpack("<d", val[:8])[0]
    return None


def _parse_summary_value(buf: bytes) -> Tuple[Optional[str], Optional[float]]:
    tag = None
    value = None
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == _WIRE_LEN:
            tag = val.decode("utf-8", errors="replace")
        elif field == 2 and wire == _WIRE_32BIT:
            value = struct.unpack("<f", val)[0]
        elif field == 8 and wire == _WIRE_LEN:
            v = _parse_tensor_scalar(val)
            if v is not None:
                value = v
    return tag, value


def _parse_event(buf: bytes) -> Tuple[float, int, List[Tuple[str, float]]]:
    wall_time = 0.0
    step = 0
    scalars: List[Tuple[str, float]] = []
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == _WIRE_64BIT:
            wall_time = struct.unpack("<d", val)[0]
        elif field == 2 and wire == _WIRE_VARINT:
            step = val
        elif field == 5 and wire == _WIRE_LEN:
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == _WIRE_LEN:
                    tag, value = _parse_summary_value(v2)
                    if tag is not None and value is not None:
                        scalars.append((tag, value))
    return wall_time, step, scalars


def read_tfevents(path: str) -> Iterator[Tuple[float, int, List[Tuple[str, float]]]]:
    """Yield (wall_time, step, [(tag, value)]) per event record."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    n = len(data)
    while pos + 12 <= n:
        (length,) = struct.unpack("<Q", data[pos : pos + 8])
        pos += 12  # length + length-crc
        if pos + length > n:
            break
        payload = data[pos : pos + length]
        pos += length + 4  # payload + payload-crc
        try:
            yield _parse_event(payload)
        except (IndexError, struct.error):
            continue  # truncated/corrupt record


def collect_tfevent_metrics(
    directory: str,
    metric_names: Sequence[str],
) -> List[MetricLog]:
    """Walk a tfevent directory and extract the named scalars
    (tfevent_loader.py MetricsCollector.parse_file). Tag matching: exact or
    trailing path component."""
    wanted = set(metric_names)
    out: List[MetricLog] = []
    for root, _dirs, files in os.walk(directory):
        for fname in sorted(files):
            if "tfevents" not in fname:
                continue
            for wall_time, step, scalars in read_tfevents(os.path.join(root, fname)):
                for tag, value in scalars:
                    name = tag if tag in wanted else tag.rsplit("/", 1)[-1]
                    if name in wanted:
                        out.append(
                            MetricLog(
                                timestamp=wall_time or float(step),
                                metric_name=name,
                                value=repr(float(value)),
                            )
                        )
    return sorted(out, key=lambda l: l.timestamp)


# -- writer ------------------------------------------------------------------
# JAX trials that want TensorBoard-compatible output (the reference's
# tf-mnist-with-summaries workload writes summaries via tf.summary) can emit
# valid event files without a TensorFlow dependency. Masked CRC32C framing
# per the TFRecord spec so real TensorBoard accepts the files.

_CRC32C_TABLE = None
# atomic per-process uniqueness for writer filenames (two in-process trial
# threads writing in the same second must not collide and truncate each other)
import itertools as _itertools

_WRITER_SEQ = _itertools.count(1)


def _crc32c(data: bytes) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
            table.append(crc)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _write_varint(v: int) -> bytes:
    if v < 0:
        v &= (1 << 64) - 1  # two's-complement int64, protobuf varint rule
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _encode_field(num: int, wire: int) -> bytes:
    return _write_varint((num << 3) | wire)


def _encode_len_field(num: int, payload: bytes) -> bytes:
    return _encode_field(num, _WIRE_LEN) + _write_varint(len(payload)) + payload


def encode_scalar_event(wall_time: float, step: int, scalars: Dict[str, float]) -> bytes:
    """Event proto bytes with TF1 simple_value scalars."""
    summary = b""
    for tag, value in scalars.items():
        val_msg = _encode_len_field(1, tag.encode())
        val_msg += _encode_field(2, _WIRE_32BIT) + struct.pack("<f", float(value))
        summary += _encode_len_field(1, val_msg)
    event = _encode_field(1, _WIRE_64BIT) + struct.pack("<d", wall_time)
    event += _encode_field(2, _WIRE_VARINT) + _write_varint(step)
    event += _encode_len_field(5, summary)
    return event


def write_scalar_events(
    directory: str,
    events: Sequence[Tuple[int, Dict[str, float]]],
    filename: Optional[str] = None,
) -> str:
    """Write (step, {tag: value}) sequences as one tfevents file; returns
    its path. Usable from any trial (no TF needed); the TfEvent collector
    and TensorBoard both read the result."""
    import time as _time

    os.makedirs(directory, exist_ok=True)
    if filename is None:
        # time alone collides for calls in the same second (TF disambiguates
        # with hostname+pid; we also need uniqueness within a process)
        filename = (
            f"events.out.tfevents.{int(_time.time())}.{os.getpid()}."
            f"{next(_WRITER_SEQ)}.katib-tpu"
        )
    path = os.path.join(directory, filename)
    base = _time.time()
    with open(path, "wb") as f:
        for i, (step, scalars) in enumerate(events):
            payload = encode_scalar_event(base + i * 1e-3, step, scalars)
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))
    return path
