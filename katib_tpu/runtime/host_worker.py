"""Multi-host trial worker — one process per host of a gang-scheduled trial.

Launched as ``python -m katib_tpu.runtime.host_worker`` by
``MultiHostExecutor`` (controller/executor.py), this is the TPU-native
equivalent of one worker pod of the reference's distributed trial CRDs
(examples/v1beta1/kubeflow-training-operator/mpijob-horovod.yaml — the
training-operator wires MASTER_ADDR/RANK into pods; here the executor wires
``KATIB_TPU_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID``, read by
``parallel.mesh.initialize_distributed``).

The worker joins the jax.distributed system, resolves the trial's
``entryPoint`` (``module:function``) and calls it with a ``WorkerContext``.
``report()`` prints ``name=value`` lines; the executor collects metrics from
process 0's stdout only, so every worker may report without duplicating
observations (the reference's PrimaryPodLabels semantics).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List, Optional


class WorkerContext:
    """Duck-typed TrialContext for gang workers (runtime/context.py)."""

    def __init__(
        self,
        trial_name: str,
        experiment_name: str,
        assignments: Dict[str, str],
        workdir: Optional[str],
        checkpoint_dir: Optional[str],
        process_id: int,
        num_processes: int,
    ):
        self.trial_name = trial_name
        self.experiment_name = experiment_name
        self.assignments = assignments
        self.workdir = workdir
        self.checkpoint_dir = checkpoint_dir
        self.process_id = process_id
        self.num_processes = num_processes
        self.topology = os.environ.get("KATIB_TPU_TOPOLOGY")
        self.labels: Dict[str, str] = {}

    def report(self, timestamp: Optional[float] = None, **metrics: float) -> None:
        for name, value in metrics.items():
            print(f"{name}={value}", flush=True)

    def param(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self.assignments.get(name, default)

    def param_float(self, name: str, default: Optional[float] = None) -> Optional[float]:
        v = self.assignments.get(name)
        return float(v) if v is not None else default

    def param_int(self, name: str, default: Optional[int] = None) -> Optional[int]:
        v = self.assignments.get(name)
        return int(float(v)) if v is not None else default

    def jax_devices(self) -> List[Any]:
        """ALL devices of the gang's distributed system (global view — the
        single-process TrialContext returns the gang-allocated subset).
        Bounded probe (utils/backend.py): a worker on a wedged backend
        fails fast instead of hanging the whole gang (KTI304)."""
        from ..utils.backend import require_devices

        return list(require_devices())

    def mesh(self, axis_names=("data",), shape=None):
        import numpy as np
        from jax.sharding import Mesh

        arr = np.array(self.jax_devices())
        if shape is None and self.topology and len(axis_names) > 1:
            from ..api.spec import parse_topology

            dims = parse_topology(self.topology)
            if dims is not None and len(dims) == len(axis_names):
                shape = tuple(dims)
        if shape is not None:
            arr = arr.reshape(shape)
        elif len(axis_names) > 1:
            raise ValueError(
                "pass shape= for multi-axis meshes (or set "
                "resources.topology with one dim per axis)"
            )
        return Mesh(arr, axis_names)

    def profile(self, enabled: Optional[bool] = None):
        # enabled=None defaults from $KATIB_TPU_PROFILE (stamped on gang
        # workers by the executor) — same contract as TrialContext.profile
        from .profiling import profile_trace

        return profile_trace(self.workdir, enabled=enabled)

    def checkpoint_store(self, subdir=None):
        """Elastic-resume store (see TrialContext.checkpoint_store). On a
        SHARED checkpoint_dir (PBT lineage), non-primary ranks write under a
        rank-<i> subdirectory so concurrent ranks never contend on the same
        checkpoint files; rank 0 owns the lineage root. Per-host workdirs
        are already disjoint."""
        from .checkpoints import store_for

        return store_for(
            self.checkpoint_dir, self.workdir, subdir, rank=self.process_id
        )


def main() -> None:
    # CPU-forced gangs (tests, CPU smoke runs): neutralize any accelerator
    # plugin that a sitecustomize registered before we ran.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from ..parallel.mesh import initialize_distributed

    initialize_distributed()

    entry = os.environ["KATIB_TPU_ENTRY_POINT"]
    mod_name, _, fn_name = entry.partition(":")
    if not fn_name:
        raise SystemExit(f"KATIB_TPU_ENTRY_POINT {entry!r} must be 'module:function'")
    fn = getattr(importlib.import_module(mod_name), fn_name)

    ctx = WorkerContext(
        trial_name=os.environ.get("KATIB_TPU_TRIAL_NAME", ""),
        experiment_name=os.environ.get("KATIB_TPU_EXPERIMENT", ""),
        assignments=json.loads(os.environ.get("KATIB_TPU_ASSIGNMENTS", "{}")),
        workdir=os.environ.get("KATIB_TPU_WORKDIR"),
        checkpoint_dir=os.environ.get("KATIB_TPU_CHECKPOINT_DIR"),
        process_id=int(os.environ.get("KATIB_TPU_PROCESS_ID", "0")),
        num_processes=int(os.environ.get("KATIB_TPU_NUM_PROCESSES", "1")),
    )
    result = fn(ctx.assignments, ctx)
    if isinstance(result, dict):  # parity with InProcessExecutor auto-report
        numeric = {k: v for k, v in result.items() if isinstance(v, (int, float))}
        if numeric:
            ctx.report(**numeric)


if __name__ == "__main__":
    main()
