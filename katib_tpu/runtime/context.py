"""Trial execution context — what a trial function receives from the runtime.

The TPU-native analogue of everything the reference injects into a trial pod
(env vars, mounted volumes, metrics sidecar wiring, suggestion PVC for PBT —
pkg/webhook/v1beta1/pod/inject_webhook.go): assignments, a push metrics
reporter with early-stopping enforcement, a workdir, the PBT checkpoint dir,
and the gang-allocated device set from which the trial builds its mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .metrics import MetricsReporter


@dataclass
class TrialContext:
    trial_name: str
    experiment_name: str
    assignments: Dict[str, str]
    reporter: MetricsReporter
    workdir: Optional[str] = None
    checkpoint_dir: Optional[str] = None
    devices: Optional[List[Any]] = None  # jax devices gang-allocated to this trial
    labels: Dict[str, str] = field(default_factory=dict)
    topology: Optional[str] = None  # resources.topology — default mesh shape
    # Scheduler hook stamped on every checkpoint save (fairshare victim
    # selection prefers recently-checkpointed trials; resume-vs-restart on
    # preemption hinges on whether a checkpoint exists at all).
    on_checkpoint: Optional[Callable[[int], None]] = None
    # Telemetry hooks (katib_tpu/telemetry.py), None when telemetry is off:
    # on_report is the watchdog heartbeat fired on every ctx.report (and on
    # subprocess output/scrape activity — the executor calls it directly);
    # on_subprocess re-points /proc sampling at the spawned child pids.
    on_report: Optional[Callable[[], None]] = None
    on_subprocess: Optional[Callable[[List[int]], None]] = None
    # Tracing (katib_tpu.tracing): bound by the scheduler when tracing is
    # on. The runtime marks the compile boundary (first report ends the
    # `compile` span and opens `steps`) and spans checkpoint saves/restores
    # and obslog flush barriers. All None when tracing is disabled — the
    # hot path then pays one attribute check per report.
    tracer: Optional[Any] = None
    trace_id: Optional[str] = None
    trace_parent: Optional[str] = None
    # AOT compile service handoff (katib_tpu/compilesvc): the WarmProgram
    # for this trial's dispatch group when the service compiled it ahead of
    # dispatch — fingerprint + the jax.stages.Compiled executable, callable
    # with concrete arrays matching the probe's avals. None when the
    # service is off, the program is cold/evicted, or the template has no
    # probe; trial code must treat it as an optional fast path and fall
    # back to its own jit (which the shared persistent XLA cache still
    # amortizes).
    compiled_program: Optional[Any] = None
    # Step clock (runtime/stepstats.py) — bound by the scheduler when
    # runtime.step_stats is on. Every report marks one step and freshly
    # completed perf windows are written through the observation store
    # under the reserved katib-tpu/perf/ namespace. None when the plane is
    # off: the hot path then pays one attribute check per report.
    step_clock: Optional[Any] = None

    def bind_trace(self, tracer, experiment: str, trace_id: str, parent_id: str) -> None:
        """Attach the trial's trace context (scheduler-side hook)."""
        self.tracer = tracer
        self.trace_id = trace_id
        self.trace_parent = parent_id
        self._trace_experiment = experiment
        self._compile_span = None
        self._steps_span = None
        self._report_count = 0

    def _trace_span(self, name: str, parent: Optional[str] = None, **attrs):
        if self.tracer is None:
            return None
        return self.tracer.start_span(
            name,
            getattr(self, "_trace_experiment", self.experiment_name),
            self.trace_id,
            parent or self.trace_parent,
            attrs=attrs or None,
        )

    def _trace_fn_start(self) -> None:
        """Executor hook: the trial function is about to run. Everything up
        to the first report is attributed to `compile` (trace-and-compile of
        the train step dominates it on JAX workloads)."""
        if self.tracer is not None:
            self._compile_span = self._trace_span("compile")
        if self.step_clock is not None:
            from . import stepstats

            self._step_clock_token = stepstats.activate([self.step_clock])

    def _trace_mark_report(self) -> None:
        """First report = compile boundary: end `compile`, open `steps`."""
        self._report_count = getattr(self, "_report_count", 0) + 1
        cs = getattr(self, "_compile_span", None)
        if cs is not None:
            self.tracer.end_span(cs, first_report=True)
            self._compile_span = None
            self._steps_span = self._trace_span("steps")

    def _trace_fn_end(self) -> None:
        """Executor hook: the trial function returned/unwound."""
        token = getattr(self, "_step_clock_token", None)
        if token is not None:
            from . import stepstats

            stepstats.deactivate(token)
            self._step_clock_token = None
        if self.tracer is None:
            return
        cs = getattr(self, "_compile_span", None)
        if cs is not None:
            # the function never reported: the whole run was one opaque
            # stretch — keep it labeled compile with the zero-report marker
            self.tracer.end_span(cs, reports=0)
            self._compile_span = None
        ss = getattr(self, "_steps_span", None)
        if ss is not None:
            self.tracer.end_span(ss, reports=getattr(self, "_report_count", 0))
            self._steps_span = None

    def report(self, **metrics: float) -> None:
        """Push metrics; raises katib_tpu.runtime.metrics.EarlyStopped when all
        early-stopping rules have tripped, TrialPreempted when the fair-share
        policy needs this trial's chips (metrics are persisted first — save
        your checkpoint BEFORE reporting and preemption loses nothing)."""
        if self.tracer is not None:
            self._trace_mark_report()
        if self.on_report is not None:
            self.on_report()  # watchdog heartbeat BEFORE a possible unwind
        sc = self.step_clock
        if sc is not None:
            from . import stepstats

            sc.mark(metrics)
            rows = sc.drain()
            if rows:
                # perf rows land BEFORE the report so the kill/preempt
                # flush barrier in MetricsReporter.report makes them
                # durable ahead of any unwind
                self.reporter.store.report_observation_log(
                    self.trial_name, stepstats.perf_logs(rows)
                )
        self.reporter.report(**metrics)

    def flush_metrics(self) -> None:
        """Durability barrier for write-behind observation stores
        (db/store.py BufferedObservationStore): returns once every metric
        reported so far is persisted. The runtime calls it on checkpoint
        save and before TrialPreempted/TrialKilled unwind; trial code only
        needs it around its own external side effects."""
        span = self._trace_span("obslog_flush") if self.tracer is not None else None
        try:
            self.reporter.store.flush()
        finally:
            if span is not None:
                self.tracer.end_span(span)

    @property
    def preempt_requested(self) -> bool:
        """True once the fair-share policy selected this trial as a
        preemption victim. Long in-step loops that rarely report can poll
        this, save a checkpoint, and call report() (which raises
        TrialPreempted) to yield their devices promptly."""
        ev = getattr(self.reporter, "preempt_event", None)
        return ev is not None and ev.is_set()

    def profile(self, enabled: Optional[bool] = None):
        """Context manager: capture a JAX profiler (xplane) trace of the
        enclosed steps into ``<workdir>/profile`` — surfaced by the UI at
        ``/api/experiments/<e>/trials/<t>/profile``. No-op without a workdir
        so trial code can call it unconditionally (SURVEY.md §5). ``enabled``
        defaults from ``$KATIB_TPU_PROFILE`` (the executor stamps it on trial
        subprocesses), so an operator can switch profiling fleet-wide without
        touching trial code; an explicit True/False wins."""
        from .profiling import profile_trace

        return profile_trace(self.workdir, enabled=enabled)

    def jax_devices(self):
        """The trial's allocated devices that are real jax.Device objects.

        The scheduler hands out abstract int slots when no accelerator is
        attached to allocation (subprocess-only experiments); trials building
        meshes must use this filtered view — empty means "use jax.devices()".
        """
        import jax

        return [d for d in (self.devices or []) if isinstance(d, jax.Device)]

    def mesh(self, axis_names=("data",), shape=None):
        """Build a jax.sharding.Mesh over this trial's allocated devices.

        Default: 1-D data mesh. Pass shape for multi-axis (e.g. shape=(2, 4),
        axis_names=("data", "model")), or set ``resources.topology``
        ("2x4") in the trial template — it becomes the default shape when
        the axis count matches.
        """
        import numpy as np
        from jax.sharding import Mesh

        devices = self.jax_devices()
        if not devices:
            from ..utils.backend import require_devices

            # bounded probe, not a raw jax.devices(): a trial building a
            # mesh on a wedged backend must fail fast, not hang (KTI304)
            devices = require_devices()
        arr = np.array(devices)
        if shape is None and self.topology and len(axis_names) > 1:
            from ..api.spec import parse_topology

            dims = parse_topology(self.topology)
            if dims is not None and len(dims) == len(axis_names):
                shape = tuple(dims)
        if shape is not None:
            arr = arr.reshape(shape)
        else:
            arr = arr.reshape((-1,) * 1)
            if len(axis_names) > 1:
                raise ValueError(
                    "pass shape= for multi-axis meshes (or set "
                    "resources.topology with one dim per axis)"
                )
        return Mesh(arr, axis_names)

    def checkpoint_store(self, subdir: Optional[str] = None):
        """Typed orbax-backed save/restore (runtime/checkpoints.py) rooted at
        this trial's checkpoint dir (the PBT lineage dir when the suggester
        provides one) or its workdir — the elastic-resume idiom: restore the
        latest step at start, save per epoch; a restarted trial
        (max_trial_restarts, PBT exploit child, controller resume) continues
        instead of starting over."""
        from .checkpoints import store_for

        store = store_for(self.checkpoint_dir, self.workdir, subdir)
        notify, orig_save, orig_restore = self.on_checkpoint, store.save, store.restore

        def _save(step, state, _notify=notify, _orig=orig_save):
            span = self._trace_span("checkpoint_save", step=int(step)) if self.tracer else None
            try:
                _orig(step, state)
            finally:
                if span is not None:
                    self.tracer.end_span(span)
            if _notify is not None:
                _notify(step)
            # every save is a durability point: a preemption decided against
            # this freshly-checkpointed trial must find its metrics on disk
            self.flush_metrics()

        def _restore(step=None, template=None, _orig=orig_restore):
            span = self._trace_span("checkpoint_restore") if self.tracer else None
            restored = None
            try:
                restored = _orig(step=step, template=template)
                return restored
            finally:
                if span is not None:
                    self.tracer.end_span(span, found=restored is not None)

        store.save = _save  # instance-level shadow; CheckpointStore API unchanged
        store.restore = _restore
        return store

    def param(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self.assignments.get(name, default)

    def param_float(self, name: str, default: Optional[float] = None) -> Optional[float]:
        v = self.assignments.get(name)
        return float(v) if v is not None else default

    def param_int(self, name: str, default: Optional[int] = None) -> Optional[int]:
        v = self.assignments.get(name)
        return int(float(v)) if v is not None else default
