"""Batched trial context — the runtime half of vmapped trial packing.

Podracer-style architectures (Anakin, arXiv:2104.06272) get their TPU
throughput by batching many identical-shape learners into ONE compiled
program; a population of same-architecture, different-scalar-hparam trials
(PBT, random/grid sweeps over optimizer knobs) is exactly that workload.
``PackedTrialContext`` is what a pack-aware trial function receives instead
of a ``TrialContext``: the K members' scalar hyperparameters are stacked
into arrays, and every ``report()`` carries per-member metric arrays that
the context demuxes back into K independent observation logs.

Member lifecycle is masking, not unwinding (ISSUE tentpole): a member whose
early-stopping rules trip, whose kill was requested, or that the train fn
marks failed is *frozen* — its reporter stops receiving demuxed rows, and
``active_mask`` flips to False so the train fn can hold its state constant
via ``jnp.where``. The pack's step loop keeps running for the remaining
members; only when no member is active does the context raise
:class:`PackFrozen` to end the loop early. Per-member terminal conditions
are derived afterwards by the PackedTrialExecutor
(katib_tpu.controller.packing).

Pack-aware functions are written once and run in BOTH modes: solo (normal
``InProcessExecutor`` fallback, string assignments, scalar reports) and
packed. ``population_of`` / ``report_population`` normalize the two so the
same vectorized math executes either way — which is also what makes the
packed-vs-sequential parity guarantee testable (identical per-member
programs, K=1 vs K>1).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .metrics import MetricsReporter


class PackFrozen(Exception):
    """Raised by PackedTrialContext.report when every member of the pack is
    frozen (stopped/killed/failed) — ends the pack's step loop early, the
    batched analogue of EarlyStopped/TrialKilled for a single trial."""


def population_of(assignments: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Normalize assignments to ``{name: float32 array of shape [K]}``.

    Packed mode already passes stacked arrays; solo mode (the
    InProcessExecutor fallback) passes the usual ``{name: str}`` dict, which
    becomes a K=1 population so the same vectorized code path runs."""
    out: Dict[str, np.ndarray] = {}
    for name, value in assignments.items():
        arr = np.asarray(
            [float(value)] if isinstance(value, (str, int, float)) else value,
            dtype=np.float32,
        )
        out[name] = arr.reshape(-1)
    return out


def uniform_param(pop: Dict[str, np.ndarray], name: str, default: float) -> float:
    """A shape-affecting parameter (batch size, epochs, ...) must be one
    value across the whole pack — members with different shapes cannot share
    a compiled program. Raises ValueError on a mixed pack so the failure is
    loud instead of silently training K members at member 0's shape."""
    arr = pop.get(name)
    if arr is None:
        return default
    values = np.unique(arr)
    if len(values) != 1:
        raise ValueError(
            f"shape-affecting parameter {name!r} differs across pack members "
            f"({sorted(float(v) for v in values)}); packable trials must "
            "agree on it (see docs/trial-packing.md)"
        )
    return float(values[0])


def report_population(ctx, **metrics) -> None:
    """Report per-member metric arrays through whichever context the trial
    function got: a PackedTrialContext takes the arrays verbatim; a solo
    TrialContext gets member 0's scalars; no context prints ``name=value``
    lines for the stdout collector (same contract as report_metrics)."""
    if ctx is not None and hasattr(ctx, "pack_size"):
        ctx.report(**metrics)
        return
    scalars = {k: float(np.asarray(v).reshape(-1)[0]) for k, v in metrics.items()}
    if ctx is not None:
        ctx.report(**scalars)
    else:
        for k, v in scalars.items():
            print(f"{k}={v}", flush=True)


@dataclass
class PackedTrialContext:
    """What a pack-aware trial function receives for a pack of K trials.

    ``assignments`` maps each parameter name to a float32 array of shape
    [K] (member order == ``trial_names`` order). Per-member workdir /
    checkpoint-dir / labels ride along as parallel lists — PBT packs need
    the per-member checkpoint lineage directories.
    """

    trial_names: List[str]
    experiment_name: str
    assignments: Dict[str, np.ndarray]
    reporters: List[MetricsReporter]
    kill_events: List[Optional[threading.Event]]
    workdirs: List[Optional[str]] = field(default_factory=list)
    checkpoint_dirs: List[Optional[str]] = field(default_factory=list)
    member_labels: List[Dict[str, str]] = field(default_factory=list)
    devices: Optional[List[Any]] = None
    topology: Optional[str] = None
    # fair-share preemption: a pack preempts as ONE unit (it holds one gang
    # allocation), so the scheduler sets every member's event together
    preempt_events: List[Optional[threading.Event]] = field(default_factory=list)
    # telemetry heartbeat (telemetry.py): the scheduler binds a callback that
    # heartbeats every member — one shared step loop, one watchdog clock
    on_report: Optional[Any] = None
    # scheduler checkpoint hook, mirroring TrialContext.on_checkpoint: the
    # fused population runtime calls it at every chunk-boundary carry save
    # so the scheduler records a checkpoint for EVERY member — a preempted
    # (device-lost) member then requeues with its observation log KEPT and
    # the resumed sweep replays only the unreported tail. Without the stamp
    # the requeue path would classify members as checkpoint-less and drop
    # their rows (they'd never be re-reported: the sweep checkpoint's
    # ``reported`` counter is already past them).
    on_checkpoint: Optional[Any] = None

    def notify_checkpoint(self, step: int = 0) -> None:
        if self.on_checkpoint is not None:
            self.on_checkpoint(step)

    def __post_init__(self) -> None:
        k = len(self.trial_names)
        self._active = [True] * k
        self._stopped = [False] * k
        self._killed = [False] * k
        self._failed = [False] * k
        self._preempted = [False] * k
        self._fail_messages: List[str] = [""] * k
        if not self.workdirs:
            self.workdirs = [None] * k
        if not self.checkpoint_dirs:
            self.checkpoint_dirs = [None] * k
        if not self.member_labels:
            self.member_labels = [{} for _ in range(k)]
        if not self.preempt_events:
            self.preempt_events = [None] * k
        self._tracer = None  # katib_tpu.tracing — bound by the scheduler
        self._trace_id = None
        self._trace_parent = None
        self._trace_experiment = ""
        self._compile_span = None
        self._steps_span = None
        self._report_count = 0
        # per-member step clocks (runtime/stepstats.py) — bound by the
        # scheduler when runtime.step_stats is on; None otherwise
        self._step_clocks: Optional[List[Any]] = None
        self._step_clock_token = None

    # -- tracing hooks (one shared program -> spans in the gang trace) -------

    def bind_trace(self, tracer, experiment: str, trace_id: str, parent_id: str) -> None:
        self._tracer = tracer
        self._trace_experiment = experiment
        self._trace_id = trace_id
        self._trace_parent = parent_id

    def _trace_fn_start(self) -> None:
        if self._tracer is not None:
            self._compile_span = self._tracer.start_span(
                "compile", self._trace_experiment, self._trace_id,
                self._trace_parent, attrs={"packSize": self.pack_size},
            )
        if self._step_clocks is not None:
            from . import stepstats

            # one shared compiled program: a recompile retraces the gang,
            # so compile events are charged to every member's clock
            self._step_clock_token = stepstats.activate(self._step_clocks)

    def _trace_mark_report(self) -> None:
        self._report_count += 1
        if self._compile_span is not None:
            self._tracer.end_span(self._compile_span, first_report=True)
            self._compile_span = None
            self._steps_span = self._tracer.start_span(
                "steps", self._trace_experiment, self._trace_id, self._trace_parent
            )

    def _trace_fn_end(self) -> None:
        if self._step_clock_token is not None:
            from . import stepstats

            stepstats.deactivate(self._step_clock_token)
            self._step_clock_token = None
        if self._tracer is None:
            return
        if self._compile_span is not None:
            self._tracer.end_span(self._compile_span, reports=0)
            self._compile_span = None
        if self._steps_span is not None:
            self._tracer.end_span(self._steps_span, reports=self._report_count)
            self._steps_span = None

    def _flush_traced(self, store) -> None:
        """Durability barrier with its `obslog_flush` span in the gang trace."""
        span = None
        if self._tracer is not None:
            span = self._tracer.start_span(
                "obslog_flush", self._trace_experiment, self._trace_id,
                self._trace_parent,
            )
        try:
            store.flush()
        finally:
            if span is not None:
                self._tracer.end_span(span)

    def record_stage(self, name: str, start: float, end: float, **attrs) -> None:
        """Record one instantaneous-or-spanning runtime stage into the gang
        trace (fused population chunks use this for their per-chunk
        compile/execute spans). No-op when tracing is off."""
        if self._tracer is not None:
            self._tracer.record_span(
                name, self._trace_experiment, self._trace_id,
                self._trace_parent, start=start, end=end, **attrs,
            )

    @property
    def pack_size(self) -> int:
        return len(self.trial_names)

    # -- traceable membership masking (ISSUE 9 tentpole) ---------------------
    #
    # The fused population runtime carries the membership mask INSIDE its
    # compiled scan (a jnp bool[K] consulted via jnp.where each generation)
    # and syncs it with this host-side context only at chunk boundaries:
    # population_mask() seeds the carry from the host view (kills, preempts,
    # early-stops absorbed so far), and absorb_population_mask() folds the
    # program's final mask back — a member the *program* deactivated (e.g.
    # divergence guard) finalizes as early-stopped rather than silently
    # completing.

    def population_mask(self):
        """The current membership mask as a jnp bool[K] array — the carried
        form of ``active_mask`` a fused program scans over."""
        import jax.numpy as jnp

        return jnp.asarray(self.active_mask)

    def absorb_population_mask(self, mask, reason: str = "deactivated by population program") -> None:
        """Fold a program-produced final mask into the host view: members
        inactive in ``mask`` but still active here are marked stopped (the
        in-program analogue of an early-stopping trip)."""
        arr = np.asarray(mask).reshape(-1)
        if arr.shape[0] != self.pack_size:
            raise ValueError(
                f"population mask has {arr.shape[0]} entries for a pack of "
                f"{self.pack_size}"
            )
        for i, alive in enumerate(arr):
            if not bool(alive) and self._active[i]:
                self._active[i] = False
                self._stopped[i] = True

    @property
    def active_mask(self) -> np.ndarray:
        """Bool [K]; True = member still training. Feed it to ``jnp.where``
        to freeze stopped members' params/metrics instead of unwinding."""
        self._sweep_kills()
        return np.array(self._active, dtype=bool)

    def member_active(self, i: int) -> bool:
        self._sweep_kills()
        return self._active[i]

    def fail_member(self, i: int, message: str) -> None:
        """Mark one member failed (bad checkpoint, invalid derived config,
        non-finite loss ...) without failing the pack: the member freezes
        and finalizes FAILED while the rest keep training."""
        if self._active[i]:
            self._active[i] = False
            self._failed[i] = True
            self._fail_messages[i] = message

    def _sweep_kills(self, preempts: bool = True) -> None:
        for i, ev in enumerate(self.kill_events):
            if self._active[i] and ev is not None and ev.is_set():
                self._active[i] = False
                self._killed[i] = True
        if not preempts:
            return
        for i, ev in enumerate(self.preempt_events):
            if self._active[i] and ev is not None and ev.is_set():
                self._active[i] = False
                self._preempted[i] = True

    def report(self, timestamp: Optional[float] = None, **metrics) -> None:
        """Demux per-member metric arrays into per-trial observation logs.

        Each value is an array of shape [K] (or a scalar, broadcast to all
        members). Frozen members are skipped — their logs end at the report
        where they stopped, exactly where a sequential run's would. All
        active members' rows land in ONE store batch (``report_many``) —
        K member appends per step would re-serialize the pack on the store
        lock that vmapping just removed from the compute. After the write,
        each member's kill event and early-stopping monitor are applied
        (same order as MetricsReporter.report: a killed/stopped member's
        final metrics are never lost), with a flush barrier before any
        member freezes on kill/preempt so its metrics are durable when the
        scheduler requeues it. Raises PackFrozen when no member remains
        active."""
        if self._tracer is not None:
            self._trace_mark_report()
        if self.on_report is not None:
            self.on_report()  # watchdog heartbeat for every member
        k = self.pack_size
        cols: Dict[str, np.ndarray] = {}
        for name, value in metrics.items():
            arr = np.asarray(value)
            if arr.ndim == 0:
                arr = np.full((k,), float(arr))
            arr = arr.reshape(-1)
            if arr.shape[0] != k:
                raise ValueError(
                    f"packed metric {name!r} has {arr.shape[0]} values for a "
                    f"pack of {k}"
                )
            cols[name] = arr
        # NO kill sweep before the write: like MetricsReporter.report,
        # a killed member's in-flight metrics are written, THEN it freezes
        # (a train fn that polls active_mask freezes earlier by choice)
        ts = timestamp if timestamp is not None else time.time()
        store = self.reporters[0].store if self.reporters else None
        batch = []
        written: List[tuple] = []  # (member index, fvals)
        for i in range(k):
            if not self._active[i]:
                continue
            member_vals = {name: float(col[i]) for name, col in cols.items()}
            fvals, logs = self.reporters[i].build_logs(member_vals, timestamp=ts)
            if self._step_clocks is not None:
                from . import stepstats

                clock = self._step_clocks[i]
                clock.mark(member_vals)
                # perf rows ride each member's batch entry: one report_many
                # keeps the pack off the store lock, and the freeze
                # barrier below makes them durable with the member's rows
                logs.extend(stepstats.perf_logs(clock.drain(), timestamp=ts))
            batch.append((self.reporters[i].trial_name, logs))
            written.append((i, fvals))
        if batch and store is not None:
            store.report_many(batch)
        freeze_barrier = False
        for i, fvals in written:
            ev = self.kill_events[i]
            if ev is not None and ev.is_set():
                self._active[i] = False
                self._killed[i] = True
                freeze_barrier = True
                continue
            pev = self.preempt_events[i]
            if pev is not None and pev.is_set():
                # like a kill, the member's in-flight metrics were written
                # first; the frozen member requeues and resumes from its
                # checkpoint, its log continuing exactly where it stopped
                self._active[i] = False
                self._preempted[i] = True
                freeze_barrier = True
                continue
            self.reporters[i].absorb(fvals)
            if self.reporters[i].stopped:
                self._active[i] = False
                self._stopped[i] = True
        if freeze_barrier and store is not None:
            # killed/preempted members leave the pack here; their final
            # metrics must be durable before the scheduler's requeue path
            # observes the freeze (same barrier MetricsReporter.report runs
            # before raising TrialKilled/TrialPreempted)
            self._flush_traced(store)
        if not any(self._active):
            if store is not None:
                self._flush_traced(store)
            raise PackFrozen(
                f"all {k} members of pack {self.trial_names} are frozen"
            )

    def note_step_seconds(self, n: int, total_seconds: float) -> None:
        """Fused-sweep chunk timing: ``n`` generations ran in one compiled
        chunk taking ``total_seconds`` — credited to every still-active
        member's step clock (the chunk IS the gang's step loop). Switches
        the clocks to external mode so the demux-time reports that follow
        do not double-count. No-op when step stats are off."""
        if self._step_clocks is None:
            return
        for i, clock in enumerate(self._step_clocks):
            if self._active[i]:
                clock.note_steps(n, total_seconds)

    # -- terminal-state views consumed by the PackedTrialExecutor ------------

    def member_outcomes(self):
        """Per-member (stopped, killed, failed, fail_message, preempted)
        after the pack function returned/unwound. Kills are swept one last
        time, preempts are NOT: a member still active after the fn returned
        finished its work, and completion beats a late preempt signal (same
        race resolution as the solo InProcessExecutor) — marking it
        preempted here would requeue and re-run a finished trial."""
        self._sweep_kills(preempts=False)
        return list(
            zip(
                self._stopped,
                self._killed,
                self._failed,
                self._fail_messages,
                self._preempted,
            )
        )

    def param_array(self, name: str, default: Optional[float] = None) -> np.ndarray:
        arr = self.assignments.get(name)
        if arr is not None:
            return arr
        if default is None:
            raise KeyError(name)
        return np.full((self.pack_size,), float(default), dtype=np.float32)
