"""End-to-end trial lifecycle tracing — spans, context propagation, export.

The reference's observability ceiling is logs plus counter/gauge Prometheus
metrics (SURVEY.md §5, prometheus_metrics.go); after vmapped packing (PR 1),
preemptive fair-share (PR 2) and the buffered obslog (PR 3) multiplied
concurrency, "where did this trial's wall-clock go?" is unanswerable from
those surfaces. Podracer-style TPU stacks (arXiv:2104.06272) live and die by
per-stage timing; this module supplies it:

- :class:`Span` — ``{trace_id, span_id, parent_id, name, start, end, attrs}``
  records collected into a bounded, thread-safe per-experiment ring;
- :class:`Tracer` — one trace per trial (root span ``trial`` from submission
  to terminal condition) with child spans for every lifecycle stage:
  suggestion, admission, queue wait, pack formation, dispatch/run, executor
  setup, first-step compile vs steady-state steps, checkpoint save/restore,
  obslog flush barriers, preemption and finalization. Packed trials get one
  gang-level trace whose root ``pack`` span has K ``member:*`` child spans;
- W3C-traceparent-style context (``00-<trace>-<span>-01``) propagated to
  subprocess trials via ``KATIB_TPU_TRACEPARENT`` and rejoined on the
  ``report_metrics`` env binding and the ReportObservationLog RPC;
- span ends feed the ``katib_span_duration_seconds{stage=...}`` histogram in
  the MetricsRegistry (controller/events.py);
- exports: span-tree text rendering (``katib-tpu trace``), Chrome/Perfetto
  ``trace_event`` JSON (``GET .../trace?format=perfetto``, openable in
  ui.perfetto.dev alongside the xplane dumps), and per-trial JSON
  persistence under ``<root>/traces/`` so traces outlive the controller.

Disabled (``runtime.tracing=false`` / ``KATIB_TPU_TRACING=0``) the tracer
costs one boolean check per call site: ``span()`` hands back a shared no-op
context manager and every ``begin_*``/``start_span`` returns None.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import re
import threading
import time
import uuid
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

ENV_TRACING = "KATIB_TPU_TRACING"
ENV_TRACEPARENT = "KATIB_TPU_TRACEPARENT"
ENV_WIRE_TRACING = "KATIB_TPU_WIRE_TRACING"

SPAN_DURATION_METRIC = "katib_span_duration_seconds"

_TRACEPARENT_RE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def tracing_enabled_from_env(default: bool = True) -> bool:
    raw = os.environ.get(ENV_TRACING)
    if raw is None or raw == "":
        return default
    return raw.lower() not in ("0", "false", "off")


def wire_tracing_from_env(default: bool = False) -> bool:
    """Client-side resolution of the wire-tracing knob (ISSUE 19): trial
    subprocesses and wire clients have no RuntimeConfig handle, so the env
    override IS the knob for them. Default off = byte-identical wire."""
    raw = os.environ.get(ENV_WIRE_TRACING)
    if raw is None or raw == "":
        return default
    return raw.lower() not in ("0", "false", "off")


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C trace-context shape (version 00, sampled flag)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) or None for a missing/malformed header."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    return m.group(1), m.group(2)


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float
    end: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return max((self.end if self.end is not None else time.time()) - self.start, 0.0)

    @property
    def ended(self) -> bool:
        return self.end is not None

    def set(self, **attrs) -> None:
        """Attach attributes mid-span (same surface as the disabled-mode
        no-op span, so call sites never branch)."""
        self.attrs.update(attrs)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "durationSeconds": round(self.duration, 6) if self.end is not None else None,
            "attrs": dict(self.attrs),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Span":
        return cls(
            trace_id=d.get("traceId", ""),
            span_id=d.get("spanId", ""),
            parent_id=d.get("parentId"),
            name=d.get("name", ""),
            start=float(d.get("start", 0.0)),
            end=None if d.get("end") is None else float(d["end"]),
            attrs=dict(d.get("attrs") or {}),
        )


class _NoopSpan:
    """Shared stand-in when tracing is disabled: every method is a no-op, so
    instrumented code never branches beyond the enabled check."""

    __slots__ = ()
    trace_id = ""
    span_id = ""

    def set(self, **attrs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class _NoopSpanCM:
    __slots__ = ()

    def __enter__(self):
        return _NOOP_SPAN

    def __exit__(self, *exc):
        return False


_NOOP_CM = _NoopSpanCM()


# current span for the context-manager API (same-thread nesting; the
# scheduler's cross-thread lifecycle spans use explicit parent ids instead)
_current_span: ContextVar[Optional[Span]] = ContextVar("katib_tpu_span", default=None)


def current_traceparent() -> Optional[str]:
    """Propagatable context: the current in-thread span if any, else the
    inherited subprocess context from $KATIB_TPU_TRACEPARENT."""
    span = _current_span.get()
    if span is not None:
        return format_traceparent(span.trace_id, span.span_id)
    tp = os.environ.get(ENV_TRACEPARENT)
    return tp if parse_traceparent(tp) else None


@dataclass
class GangTrace:
    """Handle for one pack's shared trace: root ``pack`` span plus one open
    ``member:<trial>`` child span per member (ended as members finish)."""

    trace_id: str
    root: Span
    members: Dict[str, Span]


class Tracer:
    """Bounded, thread-safe span collector with per-trial trace bookkeeping.

    One ring (deque) of spans per experiment bounds memory; completed trial
    traces are optionally persisted as one small JSON file each under
    ``persist_dir`` so ``katib-tpu trace`` works after the controller exits.
    """

    MAX_TRIAL_INDEX = 8192  # trial -> trace_id mapping bound

    def __init__(
        self,
        enabled: bool = True,
        metrics=None,
        ring_size: int = 4096,
        persist_dir: Optional[str] = None,
    ):
        self.enabled = enabled
        self.metrics = metrics
        self.ring_size = ring_size
        self.persist_dir = persist_dir
        self._lock = threading.Lock()
        self._rings: Dict[str, Deque[Span]] = {}
        # (experiment, trial) -> trace_id, insertion-ordered for the bound
        self._trial_traces: "collections.OrderedDict[Tuple[str, str], str]" = (
            collections.OrderedDict()
        )
        self._roots: Dict[str, Span] = {}  # trace_id -> root span
        # distributed plane (ISSUE 19): a WireSpanSink appends every ended
        # span durably under the SHARED root so cross-replica trees merge
        # even after this process is SIGKILLed; per-experiment annotations
        # (the failover fence token) stamp onto every later span
        self.wire_sink: Optional["WireSpanSink"] = None
        self._annotations: Dict[str, Dict[str, Any]] = {}

    def attach_wire_sink(self, sink: Optional["WireSpanSink"]) -> None:
        self.wire_sink = sink

    def annotate(self, experiment: str, **attrs: Any) -> None:
        """Merge default attrs into every span recorded for ``experiment``
        from now on — the placement failover path stamps the bumped fence
        token here so a taken-over experiment's resumed spans carry it."""
        with self._lock:
            self._annotations.setdefault(experiment, {}).update(attrs)

    # -- id + record plumbing ------------------------------------------------

    @staticmethod
    def new_trace_id() -> str:
        return uuid.uuid4().hex  # 32 hex chars — W3C trace-id width

    @staticmethod
    def new_span_id() -> str:
        return uuid.uuid4().hex[:16]  # 16 hex chars — W3C span-id width

    def _record(self, experiment: str, span: Span) -> None:
        with self._lock:
            defaults = self._annotations.get(experiment)
            ring = self._rings.get(experiment)
            if ring is None:
                ring = self._rings[experiment] = collections.deque(maxlen=self.ring_size)
            ring.append(span)
        if defaults:
            for k, v in defaults.items():
                span.attrs.setdefault(k, v)
        sink = self.wire_sink
        if sink is not None:
            span._wire_experiment = experiment  # type: ignore[attr-defined]
            if span.parent_id is None:
                # root spans are written once at open too, so a SIGKILL
                # mid-trial still leaves the victim's trace anchored
                sink.record(span, experiment)

    # -- explicit span API (cross-thread lifecycle instrumentation) ----------

    def start_span(
        self,
        name: str,
        experiment: str,
        trace_id: str,
        parent_id: Optional[str] = None,
        start: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Span]:
        if not self.enabled:
            return None
        span = Span(
            trace_id=trace_id,
            span_id=self.new_span_id(),
            parent_id=parent_id,
            name=name,
            start=time.time() if start is None else start,
            attrs=dict(attrs or {}),
        )
        self._record(experiment, span)
        return span

    def end_span(self, span: Optional[Span], end: Optional[float] = None, **attrs) -> None:
        if span is None or span.end is not None:
            return
        if attrs:
            span.attrs.update(attrs)
        span.end = time.time() if end is None else end
        if self.metrics is not None:
            try:
                self.metrics.observe(SPAN_DURATION_METRIC, span.duration, stage=span.name)
            except Exception:
                pass  # a histogram bug must never unwind the traced path
        sink = self.wire_sink
        if sink is not None:
            sink.record(span, getattr(span, "_wire_experiment", ""))

    def record_span(
        self,
        name: str,
        experiment: str,
        trace_id: str,
        parent_id: Optional[str],
        start: float,
        end: float,
        **attrs,
    ) -> Optional[Span]:
        """Record an already-measured interval (e.g. the suggestion batch
        window stamped onto every trial of the batch)."""
        span = self.start_span(
            name, experiment, trace_id, parent_id, start=start, attrs=attrs
        )
        if span is not None:
            self.end_span(span, end=end)
        return span

    # -- context-manager API (same-thread nesting) ---------------------------

    def span(
        self,
        name: str,
        experiment: str = "",
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        **attrs,
    ):
        """``with tracer.span("stage", attr=...)``: nests under the current
        in-thread span (or under the subprocess-inherited traceparent) unless
        trace_id/parent_id pin the context explicitly. Near-zero overhead
        when disabled: a shared no-op context manager is returned."""
        if not self.enabled:
            return _NOOP_CM
        return _SpanCM(self, name, experiment, trace_id, parent_id, attrs)

    # -- trial lifecycle -----------------------------------------------------

    def begin_trial(
        self, experiment: str, trial: str, start: Optional[float] = None, **attrs
    ) -> Optional[Span]:
        """Open (or return the still-open) root span of the trial's trace."""
        if not self.enabled:
            return None
        with self._lock:
            trace_id = self._trial_traces.get((experiment, trial))
            root = self._roots.get(trace_id) if trace_id else None
        if root is not None and root.end is None:
            return root  # resubmit of an in-flight trace (resume path)
        sink = self.wire_sink
        if root is None and sink is not None:
            adopted = sink.adopt_trial_root(experiment, trial)
            if adopted is not None:
                # failover resume (ISSUE 19): rejoin the dead replica's
                # still-open trace so victim + takeover spans merge into ONE
                # cross-replica tree; per-experiment annotations (the bumped
                # fence token) stamp onto the adopted root via _record
                adopted.attrs.update(attrs)
                self._record(experiment, adopted)
                with self._lock:
                    self._trial_traces[(experiment, trial)] = adopted.trace_id
                    self._trial_traces.move_to_end((experiment, trial))
                    while len(self._trial_traces) > self.MAX_TRIAL_INDEX:
                        _, old_trace = self._trial_traces.popitem(last=False)
                        self._roots.pop(old_trace, None)
                    self._roots[adopted.trace_id] = adopted
                return adopted
        trace_id = self.new_trace_id()
        root = Span(
            trace_id=trace_id,
            span_id=self.new_span_id(),
            parent_id=None,
            name="trial",
            start=time.time() if start is None else start,
            attrs={"experiment": experiment, "trial": trial, **attrs},
        )
        self._record(experiment, root)
        with self._lock:
            self._trial_traces[(experiment, trial)] = trace_id
            self._trial_traces.move_to_end((experiment, trial))
            while len(self._trial_traces) > self.MAX_TRIAL_INDEX:
                _, old_trace = self._trial_traces.popitem(last=False)
                self._roots.pop(old_trace, None)
            self._roots[trace_id] = root
        return root

    def trial_root(self, experiment: str, trial: str) -> Optional[Span]:
        if not self.enabled:
            return None
        with self._lock:
            trace_id = self._trial_traces.get((experiment, trial))
            return self._roots.get(trace_id) if trace_id else None

    def end_trial(self, experiment: str, trial: str, **attrs) -> None:
        """End the trial's root span (idempotent) and persist the trace."""
        root = self.trial_root(experiment, trial)
        if root is None or root.end is not None:
            return
        self.end_span(root, **attrs)
        self._persist(experiment, trial, root.trace_id)

    def begin_gang(
        self, experiment: str, pack_id: str, trials: Sequence[str]
    ) -> Optional[GangTrace]:
        """One gang-level trace per pack: root ``pack`` span with K open
        ``member:<trial>`` children, each linked to the member's own trial
        trace via the ``trialTraceId`` attr."""
        if not self.enabled:
            return None
        trace_id = self.new_trace_id()
        root = Span(
            trace_id=trace_id,
            span_id=self.new_span_id(),
            parent_id=None,
            name="pack",
            start=time.time(),
            attrs={"experiment": experiment, "pack": pack_id, "members": len(trials)},
        )
        self._record(experiment, root)
        members: Dict[str, Span] = {}
        for name in trials:
            trial_root = self.trial_root(experiment, name)
            m = Span(
                trace_id=trace_id,
                span_id=self.new_span_id(),
                parent_id=root.span_id,
                name=f"member:{name}",
                start=root.start,
                attrs={
                    "trial": name,
                    "trialTraceId": trial_root.trace_id if trial_root else None,
                },
            )
            self._record(experiment, m)
            members[name] = m
        return GangTrace(trace_id=trace_id, root=root, members=members)

    # -- queries / export ----------------------------------------------------

    def trace_spans(self, experiment: str, trace_id: str) -> List[Span]:
        with self._lock:
            ring = self._rings.get(experiment, ())
            return [s for s in ring if s.trace_id == trace_id]

    def trial_trace(self, experiment: str, trial: str) -> Optional[Dict[str, Any]]:
        """``{"traceId", "experiment", "trial", "spans": [...]}`` from the
        live ring, falling back to the persisted file; None when unknown."""
        with self._lock:
            trace_id = self._trial_traces.get((experiment, trial))
        if trace_id:
            spans = self.trace_spans(experiment, trace_id)
            if spans:
                return {
                    "traceId": trace_id,
                    "experiment": experiment,
                    "trial": trial,
                    "spans": [s.to_dict() for s in spans],
                }
        return self._load_persisted(experiment, trial)

    def forget(self, experiment: str) -> None:
        with self._lock:
            self._rings.pop(experiment, None)
            for key in [k for k in self._trial_traces if k[0] == experiment]:
                self._roots.pop(self._trial_traces.pop(key), None)

    # -- persistence ---------------------------------------------------------

    def _trace_path(self, experiment: str, trial: str) -> Optional[str]:
        if not self.persist_dir:
            return None
        bad = any(
            "/" in n or "\\" in n or ".." in n or "\x00" in n or not n
            for n in (experiment, trial)
        )
        if bad:
            return None
        return os.path.join(self.persist_dir, experiment, f"{trial}.json")

    def _persist(self, experiment: str, trial: str, trace_id: str) -> None:
        path = self._trace_path(experiment, trial)
        if path is None:
            return
        payload = {
            "traceId": trace_id,
            "experiment": experiment,
            "trial": trial,
            "spans": [s.to_dict() for s in self.trace_spans(experiment, trace_id)],
        }
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            logging.getLogger("katib_tpu.tracing").warning(
                "failed to persist trace for %s/%s", experiment, trial, exc_info=True
            )

    def _load_persisted(self, experiment: str, trial: str) -> Optional[Dict[str, Any]]:
        path = self._trace_path(experiment, trial)
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class _SpanCM:
    """Context manager returned by Tracer.span when enabled."""

    __slots__ = ("_tracer", "_name", "_experiment", "_trace_id", "_parent_id", "_attrs", "_span", "_token")

    def __init__(self, tracer, name, experiment, trace_id, parent_id, attrs):
        self._tracer = tracer
        self._name = name
        self._experiment = experiment
        self._trace_id = trace_id
        self._parent_id = parent_id
        self._attrs = attrs
        self._span = None
        self._token = None

    def __enter__(self) -> Span:
        trace_id, parent_id = self._trace_id, self._parent_id
        if trace_id is None:
            parent = _current_span.get()
            if parent is not None:
                trace_id, parent_id = parent.trace_id, parent.span_id
            else:
                inherited = parse_traceparent(os.environ.get(ENV_TRACEPARENT))
                if inherited is not None:
                    trace_id, parent_id = inherited
                else:
                    trace_id = Tracer.new_trace_id()
        self._span = self._tracer.start_span(
            self._name, self._experiment, trace_id, parent_id, attrs=self._attrs
        )
        self._token = _current_span.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        _current_span.reset(self._token)
        self._tracer.end_span(
            self._span, **({"error": exc_type.__name__} if exc_type else {})
        )
        return False


# -- process-global tracer (subprocess trials, RPC services) -----------------

_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    """Lazily-created process tracer for code with no controller handle:
    subprocess trials that inherited $KATIB_TPU_TRACEPARENT, and the gRPC
    service side of the ReportObservationLog rejoin."""
    global _default_tracer
    with _default_lock:
        if _default_tracer is None:
            _default_tracer = Tracer(enabled=tracing_enabled_from_env())
        return _default_tracer


def record_env_report(n_metrics: int) -> Optional[Span]:
    """Rejoin point for the report_metrics env binding: a subprocess trial's
    push lands a ``report_metrics`` span in the child's tracer carrying the
    controller-issued trace/parent ids, so merged views form one tree."""
    ctx = parse_traceparent(os.environ.get(ENV_TRACEPARENT))
    if ctx is None:
        return None
    tracer = default_tracer()
    if not tracer.enabled:
        return None
    trace_id, parent_id = ctx
    experiment = os.environ.get("KATIB_TPU_EXPERIMENT", "") or "_remote"
    span = tracer.start_span(
        "report_metrics", experiment, trace_id, parent_id,
        attrs={"metrics": int(n_metrics)},
    )
    tracer.end_span(span)
    return span


# -- structured logging ------------------------------------------------------

_log_ctx: ContextVar[Optional[Dict[str, str]]] = ContextVar(
    "katib_tpu_log_ctx", default=None
)


def push_log_context(**fields: str):
    """Stamp experiment=/trial=/trace_id= onto subsequent log lines of this
    thread (loggers wired via install_log_context). Returns a token for
    pop_log_context."""
    merged = dict(_log_ctx.get() or {})
    merged.update({k: v for k, v in fields.items() if v})
    return _log_ctx.set(merged)


def pop_log_context(token) -> None:
    _log_ctx.reset(token)


@contextlib.contextmanager
def log_context(**fields: str):
    token = push_log_context(**fields)
    try:
        yield
    finally:
        pop_log_context(token)


class TraceContextFilter(logging.Filter):
    """Appends the ambient trial context to log lines —
    ``... [experiment=e trial=t trace_id=abc]`` — so concurrent trials'
    interleaved controller/runtime logs are attributable."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = _log_ctx.get()
        if ctx:
            suffix = " ".join(f"{k}={v}" for k, v in ctx.items())
            record.msg = f"{record.msg} [{suffix}]"
        return True


_installed_loggers: set = set()
_installed_loggers_lock = threading.Lock()

LOGGERS = (
    "katib_tpu.scheduler",
    "katib_tpu.executor",
    "katib_tpu.experiment",
)


def install_log_context(*names: str) -> None:
    """Idempotently wire the context filter into the named loggers (default:
    scheduler + executor + experiment). Locked: two controllers constructed
    concurrently (tests do this) must not double-install a filter through
    the check-then-add race."""
    with _installed_loggers_lock:
        for name in names or LOGGERS:
            if name in _installed_loggers:
                continue
            _installed_loggers.add(name)
            logging.getLogger(name).addFilter(TraceContextFilter())


# -- distributed tracing plane (ISSUE 19) ------------------------------------
#
# When runtime.wire_tracing is on, every ended span is appended as one JSON
# line under the SHARED state root: <root>/traces/wire/<trace_id>/<replica>
# .jsonl. Append-only jsonl is the crash-durability idiom here (a torn last
# line is skipped by the reader; KTI305's tmp+os.replace applies to whole-
# file rewrites, not logs), and the directory key IS the trace id, so a
# cross-replica merge is one readdir — no matter which replica died when.

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
_SAFE_COMPONENT_RE = re.compile(r"[^A-Za-z0-9._-]")

WIRE_TRACEPARENT_HEADER = "X-Katib-Traceparent"
# adversarial bound: headers/frame fields longer than this are ignored
# loudly rather than parsed (a valid traceparent is exactly 55 bytes)
MAX_TRACEPARENT_LEN = 128


class WireSpanSink:
    """Durable, replica-tagged span appender on the shared state root.

    One jsonl file per (trace, replica); records carry experiment/trial/
    replica alongside the span so offline merges need no other index. Write
    failures are logged once and never unwind the traced path.
    """

    def __init__(self, root_dir: str, replica: str):
        self.root_dir = root_dir
        self.dir = os.path.join(root_dir, "traces", "wire")
        self.replica = _SAFE_COMPONENT_RE.sub("_", replica or "replica") or "replica"
        self._lock = threading.Lock()
        self._error_logged = False

    def record(self, span: Span, experiment: str = "") -> None:
        if not _TRACE_ID_RE.match(span.trace_id or ""):
            return
        rec = span.to_dict()
        rec["experiment"] = experiment
        rec["trial"] = span.attrs.get("trial", "")
        rec["replica"] = self.replica
        line = json.dumps(rec) + "\n"
        path = os.path.join(self.dir, span.trace_id, f"{self.replica}.jsonl")
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with self._lock, open(path, "a") as f:
                f.write(line)
                f.flush()
        except OSError:
            if not self._error_logged:
                self._error_logged = True
                logging.getLogger("katib_tpu.tracing").warning(
                    "wire span sink write failed under %s (logged once)",
                    self.dir, exc_info=True,
                )
            return
        if (
            span.parent_id is None
            and span.end is None
            and span.name == "trial"
            and span.attrs.get("trial")
        ):
            # trial-root index: one append per begin_trial, sharded per
            # experiment, so a takeover replica can find the victim's
            # still-open trace and REJOIN it instead of forking a new one
            try:
                entry = json.dumps({
                    "trial": span.attrs["trial"],
                    "traceId": span.trace_id,
                    "spanId": span.span_id,
                })
                ipath = self._trial_index_path(experiment)
                os.makedirs(os.path.dirname(ipath), exist_ok=True)
                with self._lock, open(ipath, "a") as f:
                    f.write(entry + "\n")
                    f.flush()
            except OSError:
                pass  # adoption degrades to a fresh trace; spans still merge

    def _trial_index_path(self, experiment: str) -> str:
        safe = _SAFE_COMPONENT_RE.sub("_", experiment or "_") or "_"
        return os.path.join(self.dir, "_trials", safe + ".jsonl")

    def adopt_trial_root(self, experiment: str, trial: str) -> Optional[Span]:
        """The failover-resume rejoin point: the most recent STILL-OPEN root
        span another replica recorded for (experiment, trial), or None when
        the trial was never traced or ended cleanly (a re-run then starts
        its own trace — adopting a finished tree would conflate two runs)."""
        best: Optional[Dict[str, Any]] = None
        try:
            with open(self._trial_index_path(experiment)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail line from a SIGKILLed writer
                    if rec.get("trial") == trial and rec.get("traceId"):
                        best = rec  # last wins: the newest begin_trial
        except OSError:
            return None
        if best is None:
            return None
        for rec in load_wire_records(self.root_dir, best["traceId"]):
            if rec.get("spanId") == best.get("spanId"):
                if rec.get("end") is not None:
                    return None  # ended cleanly: nothing to resume
                return Span.from_dict(rec)
        return None


def load_wire_records(root_dir: str, trace_id: str) -> List[Dict[str, Any]]:
    """All replicas' records for one trace, deduped by spanId (an ended
    record supersedes the open root record written at span start)."""
    if not _TRACE_ID_RE.match((trace_id or "").lower()):
        return []
    tdir = os.path.join(root_dir, "traces", "wire", trace_id.lower())
    by_span: Dict[str, Dict[str, Any]] = {}
    try:
        files = sorted(os.listdir(tdir))
    except OSError:
        return []
    for fname in files:
        if not fname.endswith(".jsonl"):
            continue
        try:
            with open(os.path.join(tdir, fname)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail line from a SIGKILLed writer
                    sid = rec.get("spanId")
                    if not sid:
                        continue
                    prev = by_span.get(sid)
                    if prev is None or (prev.get("end") is None and rec.get("end") is not None):
                        by_span[sid] = rec
        except OSError:
            continue
    return sorted(by_span.values(), key=lambda r: r.get("start", 0.0))


def merge_trace(root_dir: Optional[str], trace: Optional[Dict[str, Any]],
                trace_id: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """One coherent cross-replica tree: the per-trial persisted/ring trace
    (may be None for a SIGKILLed victim) unioned with every replica's wire
    records for the trace id, deduped by spanId."""
    tid = (trace or {}).get("traceId") or trace_id
    if not tid:
        return trace
    merged: Dict[str, Dict[str, Any]] = {}
    for s in (trace or {}).get("spans", []):
        if s.get("spanId"):
            merged[s["spanId"]] = s
    replicas = set()
    if root_dir:
        for rec in load_wire_records(root_dir, tid):
            if rec.get("replica"):
                replicas.add(rec["replica"])
            prev = merged.get(rec.get("spanId"))
            if prev is None or (prev.get("end") is None and rec.get("end") is not None):
                merged[rec["spanId"]] = rec
    if not merged:
        return trace
    out = dict(trace or {"traceId": tid})
    out["traceId"] = tid
    out["spans"] = sorted(merged.values(), key=lambda s: s.get("start", 0.0))
    if replicas:
        out["replicas"] = sorted(replicas)
    return out


def experiment_traces(root_dir: str, experiment: str) -> List[Dict[str, Any]]:
    """All of one experiment's merged traces, worst-first by root-span
    duration: per-trial persisted traces under ``<root>/traces/<exp>/``
    unioned with wire records, plus wire-only traces (a victim replica's
    trials that never reached end_trial persistence)."""
    traces: List[Dict[str, Any]] = []
    seen_tids: set = set()
    exp_dir = os.path.join(root_dir, "traces", experiment)
    try:
        trial_files = sorted(os.listdir(exp_dir))
    except OSError:
        trial_files = []
    for fname in trial_files:
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(exp_dir, fname)) as f:
                trace = json.load(f)
        except (OSError, ValueError):
            continue
        merged = merge_trace(root_dir, trace)
        if merged:
            traces.append(merged)
            if merged.get("traceId"):
                seen_tids.add(merged["traceId"])
    # wire-only traces: scan the by-trace dirs and keep those whose records
    # name this experiment (bounded by what the sweep actually wrote)
    wdir = os.path.join(root_dir, "traces", "wire")
    try:
        tids = sorted(os.listdir(wdir))
    except OSError:
        tids = []
    for tid in tids:
        if tid in seen_tids or not _TRACE_ID_RE.match(tid):
            continue
        recs = load_wire_records(root_dir, tid)
        mine = [r for r in recs if r.get("experiment") == experiment]
        if not mine:
            continue
        trials = sorted({r["trial"] for r in mine if r.get("trial")})
        replicas = sorted({r["replica"] for r in recs if r.get("replica")})
        traces.append({
            "traceId": tid,
            "experiment": experiment,
            "trial": trials[0] if len(trials) == 1 else ",".join(trials),
            "spans": recs,
            "replicas": replicas,
        })

    def _root_duration(trace: Dict[str, Any]) -> float:
        spans = [Span.from_dict(s) for s in trace.get("spans", [])]
        roots, _ = build_tree(spans)
        return max((r.duration for r in roots), default=0.0)

    for t in traces:
        t["rootDurationSeconds"] = round(_root_duration(t), 6)
    traces.sort(key=lambda t: t["rootDurationSeconds"], reverse=True)
    return traces


def parse_slo_objectives(spec: str) -> Dict[str, float]:
    """``"default=0.5,CreateExperiment=2.0"`` -> per-method latency
    objectives in seconds; malformed parts are dropped loudly (a typo'd
    objective must not take down the server)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        method, _, raw = part.partition("=")
        try:
            value = float(raw)
        except ValueError:
            logging.getLogger("katib_tpu.tracing").warning(
                "ignoring malformed SLO objective %r (want Method=seconds)", part
            )
            continue
        if method.strip() and value > 0:
            out[method.strip()] = value
    return out


class FlightRecorder:
    """Bounded worst-N slow-RPC ring: each entry keeps the request's method,
    tenant, latency and its span tree, dumpable via GET /api/fleet/slow and
    on SIGUSR2. Admission is by latency — once full, a new request must beat
    the fastest retained entry."""

    def __init__(self, size: int = 32):
        self.size = max(int(size), 0)
        self._lock = threading.Lock()
        self._entries: List[Dict[str, Any]] = []  # sorted slowest-first

    def record(
        self,
        method: str,
        duration: float,
        tenant: str = "",
        trace_id: str = "",
        code: int = 200,
        spans: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        if self.size <= 0:
            return
        entry = {
            "method": method,
            "tenant": tenant,
            "durationSeconds": round(duration, 6),
            "traceId": trace_id,
            "code": code,
            "time": time.time(),
            "spans": spans or [],
        }
        with self._lock:
            if len(self._entries) >= self.size and duration <= self._entries[-1]["durationSeconds"]:
                return
            self._entries.append(entry)
            self._entries.sort(key=lambda e: e["durationSeconds"], reverse=True)
            del self._entries[self.size:]

    def dump(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._entries]


# -- export: span tree + Perfetto --------------------------------------------

def build_tree(spans: Sequence[Span]):
    """(roots, children) with children keyed by span_id, both in start
    order; spans whose parent is absent from the set are treated as roots."""
    by_id = {s.span_id: s for s in spans}
    children: Dict[str, List[Span]] = {}
    roots: List[Span] = []
    for s in sorted(spans, key=lambda s: s.start):
        if s.parent_id and s.parent_id in by_id:
            children.setdefault(s.parent_id, []).append(s)
        else:
            roots.append(s)
    return roots, children


def render_tree(spans: Sequence[Span]) -> str:
    """Indented span tree with durations and % of the trial wall-clock —
    the ``katib-tpu trace`` CLI view."""
    if not spans:
        return "(no spans)"
    roots, children = build_tree(spans)
    total = max((r.duration for r in roots), default=0.0) or 1e-9
    width = max(len(s.name) for s in spans) + 2
    lines: List[str] = []

    def _walk(span: Span, depth: int) -> None:
        pct = span.duration / total * 100.0
        label = ("  " * depth + span.name).ljust(width + depth * 2)
        note = "" if span.ended else "  (open)"
        keys = {
            k: v
            for k, v in span.attrs.items()
            if k not in ("experiment", "trial") and v not in (None, "")
        }
        attrs = f"  {keys}" if keys else ""
        lines.append(f"{label}{span.duration:>9.3f}s  {pct:>5.1f}%{note}{attrs}")
        for child in children.get(span.span_id, []):
            _walk(child, depth + 1)

    for root in roots:
        _walk(root, 0)
    return "\n".join(lines)


def to_perfetto(spans: Sequence[Span], trace_name: str = "katib-tpu") -> Dict[str, Any]:
    """Chrome ``trace_event`` JSON (the Trace Event Format consumed by
    ui.perfetto.dev and chrome://tracing): complete ``X`` events in
    microseconds, with sibling spans that overlap in time pushed onto
    separate ``tid`` lanes so nesting stays well-formed."""
    now = time.time()
    roots, children = build_tree(spans)
    events: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "tid": 0,
            "args": {"name": trace_name},
        }
    ]
    lanes: Dict[int, List[Tuple[float, float]]] = {}  # tid -> placed intervals

    def _fits(tid: int, start: float, end: float) -> bool:
        for s0, e0 in lanes.get(tid, ()):
            disjoint = end <= s0 or start >= e0
            contains = s0 <= start and end <= e0
            contained = start <= s0 and e0 <= end
            if not (disjoint or contains or contained):
                return False
        return True

    def _place(span: Span, parent_tid: int) -> None:
        start = span.start
        end = span.end if span.end is not None else now
        tid = parent_tid
        while not _fits(tid, start, end):
            tid += 1
        lanes.setdefault(tid, []).append((start, end))
        events.append(
            {
                "name": span.name,
                "cat": "trial",
                "ph": "X",
                "ts": span.start * 1e6,
                "dur": max(end - start, 0.0) * 1e6,
                "pid": 1,
                "tid": tid,
                "args": {
                    "traceId": span.trace_id,
                    "spanId": span.span_id,
                    **{k: v for k, v in span.attrs.items() if v is not None},
                },
            }
        )
        for child in children.get(span.span_id, []):
            _place(child, tid)

    for root in roots:
        _place(root, 1)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
