from .katib_client import KatibClient  # noqa: F401
from . import search  # noqa: F401
