"""KatibClient — the user-facing SDK surface.

reference sdk/python/v1beta1/kubeflow/katib/api/katib_client.py (1298 LoC):
create_experiment, tune() (objective function -> experiment), waiting and
condition helpers, optimal-HP getters, trial metrics from the DB manager,
budget edits. Here the client drives an in-process ExperimentController
instead of the K8s API, but method names and semantics track the SDK so a
Katib user can port scripts mechanically.

tune() differences from the reference (katib_client.py:163-434): the
reference serializes the objective function's source into a container
command; the TPU-native fast path passes the callable straight into the trial
template (in-process execution under the trial's device allocation). Pass
``pack=True`` to instead serialize the function source and run it as a
subprocess trial with stdout metric collection — the reference's exact
topology — which also exercises the placeholder-template path.
"""

from __future__ import annotations

import inspect
import os
import textwrap
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ..api.spec import (
    AlgorithmSetting,
    AlgorithmSpec,
    EarlyStoppingSpec,
    ExperimentSpec,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    TrialParameterSpec,
    TrialResources,
    TrialTemplate,
)
from ..api.status import Condition, Experiment, SuggestionState, Trial
from ..controller.experiment import ExperimentController
from ..db.store import MetricLog


class KatibClient:
    def __init__(
        self,
        root_dir: Optional[str] = None,
        devices: Optional[Sequence[Any]] = None,
        controller: Optional[ExperimentController] = None,
    ):
        self.controller = controller or ExperimentController(root_dir=root_dir, devices=devices)

    # -- experiment lifecycle (katib_client.py create_experiment etc.) ------

    def create_experiment(self, spec: ExperimentSpec) -> Experiment:
        return self.controller.create_experiment(spec)

    def get_experiment(self, name: str) -> Optional[Experiment]:
        return self.controller.state.get_experiment(name)

    def list_experiments(self) -> List[Experiment]:
        return self.controller.state.list_experiments()

    def delete_experiment(self, name: str) -> None:
        self.controller.delete_experiment(name)

    def edit_experiment_budget(self, name: str, **kw) -> Experiment:
        return self.controller.edit_experiment_budget(name, **kw)

    def run(self, name: str, timeout: Optional[float] = None) -> Experiment:
        """Drive to completion (the reference's controllers run server-side;
        in-process the client pumps the loop)."""
        return self.controller.run(name, timeout=timeout)

    def wait_for_experiment_condition(
        self,
        name: str,
        expected_condition: str = "Succeeded",
        timeout: float = 600,
        polling_interval: float = 1.0,
    ) -> Experiment:
        """katib_client.py wait_for_experiment_condition."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            exp = self.get_experiment(name)
            if exp is not None and exp.status.condition.value == expected_condition:
                return exp
            if exp is not None and exp.status.is_completed:
                raise RuntimeError(
                    f"experiment {name!r} reached {exp.status.condition.value}, "
                    f"expected {expected_condition}"
                )
            time.sleep(polling_interval)
        raise TimeoutError(f"experiment {name!r} not {expected_condition} within {timeout}s")

    def get_experiment_conditions(self, name: str) -> List[Condition]:
        """katib_client.py get_experiment_conditions: a snapshot of the
        condition history (type/status/reason/message/lastTransitionTime);
        copied so later controller transitions don't mutate it under the
        caller."""
        exp = self.get_experiment(name)
        if exp is None:
            return []
        return [Condition.from_dict(c.to_dict()) for c in exp.status.conditions]

    def is_experiment_created(self, name: str) -> bool:
        """True once the experiment exists in the state store. The reference
        checks for a Created condition with status True
        (katib_client.py:568-597); here creation is synchronous, so existence
        is the same signal."""
        return self.get_experiment(name) is not None

    def is_experiment_running(self, name: str) -> bool:
        exp = self.get_experiment(name)
        return bool(exp and exp.status.condition.value == "Running")

    def is_experiment_restarting(self, name: str) -> bool:
        exp = self.get_experiment(name)
        return bool(exp and exp.status.condition.value == "Restarting")

    def is_experiment_succeeded(self, name: str) -> bool:
        exp = self.get_experiment(name)
        return bool(exp and exp.status.is_succeeded)

    def is_experiment_failed(self, name: str) -> bool:
        exp = self.get_experiment(name)
        return bool(exp and exp.status.condition.value == "Failed")

    # -- suggestions (katib_client.py get_suggestion/list_suggestions) -------

    def get_suggestion(self, name: str) -> Optional[SuggestionState]:
        """The per-experiment suggestion state: demand counter, produced
        assignments, algorithm-settings feedback (suggestion_types.go:29-150)."""
        return self.controller.state.get_suggestion(name)

    def list_suggestions(self) -> List[SuggestionState]:
        """One SuggestionState per experiment that has requested assignments."""
        out = []
        for exp in self.list_experiments():
            s = self.controller.state.get_suggestion(exp.name)
            if s is not None:
                out.append(s)
        return out

    # -- results -------------------------------------------------------------

    def get_trial(self, experiment_name: str, trial_name: str) -> Optional[Trial]:
        """katib_client.py get_trial."""
        return self.controller.state.get_trial(experiment_name, trial_name)

    def list_trials(self, name: str) -> List[Trial]:
        return self.controller.state.list_trials(name)

    def get_success_trial_details(self, name: str) -> List[Dict[str, Any]]:
        """katib_client.py get_success_trial_details."""
        out = []
        for t in self.list_trials(name):
            if t.is_succeeded:
                out.append(
                    {
                        "name": t.name,
                        "parameter_assignments": t.assignments_dict(),
                        "metrics": [m.to_dict() for m in (t.observation.metrics if t.observation else [])],
                    }
                )
        return out

    def get_optimal_hyperparameters(self, name: str) -> Dict[str, Any]:
        """katib_client.py get_optimal_hyperparameters."""
        exp = self.get_experiment(name)
        if exp is None:
            raise KeyError(name)
        opt = exp.status.current_optimal_trial
        return {
            "best_trial_name": opt.best_trial_name,
            "parameter_assignments": {a.name: a.value for a in opt.parameter_assignments},
            "observation": opt.observation.to_dict(),
        }

    def get_trial_metrics(self, trial_name: str, metric_name: Optional[str] = None) -> List[MetricLog]:
        """katib_client.py get_trial_metrics (reads the DB manager)."""
        return self.controller.obs_store.get_observation_log(trial_name, metric_name=metric_name)

    # -- tune ---------------------------------------------------------------

    def tune(
        self,
        name: str,
        objective: Callable[..., Any],
        parameters: Dict[str, ParameterSpec],
        objective_metric_name: str,
        additional_metric_names: Optional[List[str]] = None,
        objective_type: str = "maximize",
        objective_goal: Optional[float] = None,
        algorithm_name: str = "random",
        algorithm_settings: Optional[Dict[str, Any]] = None,
        early_stopping_algorithm_name: Optional[str] = None,
        early_stopping_settings: Optional[Dict[str, Any]] = None,
        max_trial_count: Optional[int] = None,
        parallel_trial_count: Optional[int] = None,
        max_failed_trial_count: Optional[int] = None,
        num_devices_per_trial: int = 1,
        num_hosts_per_trial: int = 1,
        retain_trials: bool = False,
        pack: bool = False,
        env: Optional[Dict[str, str]] = None,
        success_condition: str = "",
        failure_condition: str = "",
    ) -> Experiment:
        """Turn a Python objective function into an Experiment
        (katib_client.py tune, :163-434).

        ``objective`` receives a dict of hyperparameters (plus optionally the
        trial context as a second argument) and reports metrics via
        katib_tpu.report_metrics or by returning a metric dict.
        ``parameters`` maps names to katib_tpu.client.search builders.

        ``num_hosts_per_trial > 1`` gang-schedules each trial across worker
        processes (jax.distributed) — requires ``pack=True`` (an in-memory
        callable cannot span processes). ``success_condition`` /
        ``failure_condition`` define trial-state predicates
        (controller/conditions.py); stdout-based conditions also require
        ``pack=True``.
        """
        named_params = []
        for pname, pspec in parameters.items():
            ps = ParameterSpec(
                name=pname, parameter_type=pspec.parameter_type, feasible_space=pspec.feasible_space
            )
            named_params.append(ps)

        resources = TrialResources(
            num_devices=num_devices_per_trial, num_hosts=num_hosts_per_trial
        )
        if pack:
            template = self._packed_template(objective, named_params, env or {})
            template.resources = resources
            template.retain = retain_trials
        else:
            fn = objective
            try:
                sig_params = inspect.signature(fn).parameters.values()
                n_positional = sum(
                    1
                    for p in sig_params
                    if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                ) + 2 * any(p.kind == p.VAR_POSITIONAL for p in sig_params)
            except (TypeError, ValueError):  # C callables etc.: assume (assignments, ctx)
                n_positional = 2
            if n_positional <= 1:
                wrapped = lambda assignments, ctx: fn(assignments)
            else:
                wrapped = fn
            template = TrialTemplate(
                function=wrapped,
                resources=resources,
                retain=retain_trials,
            )
        template.success_condition = success_condition
        template.failure_condition = failure_condition

        spec = ExperimentSpec(
            name=name,
            parameters=named_params,
            objective=ObjectiveSpec(
                type=ObjectiveType(objective_type),
                goal=objective_goal,
                objective_metric_name=objective_metric_name,
                additional_metric_names=list(additional_metric_names or []),
            ),
            algorithm=AlgorithmSpec(
                algorithm_name=algorithm_name,
                algorithm_settings=[
                    AlgorithmSetting(k, str(v)) for k, v in (algorithm_settings or {}).items()
                ],
            ),
            early_stopping=(
                EarlyStoppingSpec(
                    algorithm_name=early_stopping_algorithm_name,
                    algorithm_settings=[
                        AlgorithmSetting(k, str(v))
                        for k, v in (early_stopping_settings or {}).items()
                    ],
                )
                if early_stopping_algorithm_name
                else None
            ),
            trial_template=template,
            max_trial_count=max_trial_count,
            parallel_trial_count=parallel_trial_count,
            max_failed_trial_count=max_failed_trial_count,
        )
        return self.create_experiment(spec)

    def _packed_template(
        self, objective: Callable, parameters: List[ParameterSpec], env: Dict[str, str]
    ) -> TrialTemplate:
        """Serialize the objective source into a subprocess command — the
        reference topology (katib_client.py:325-345 builds a container command
        from inspect.getsource). Parameter values travel as argv
        ``name=value`` pairs, never as source text, so arbitrary value strings
        cannot break (or inject into) the generated script."""
        import sys

        src = textwrap.dedent(inspect.getsource(objective))
        fn_name = objective.__name__
        script = (
            "import sys\n"
            + src
            + "\n"
            + "params = dict(a.split('=', 1) for a in sys.argv[1:])\n"
            + f"result = {fn_name}(params)\n"
            + "if isinstance(result, dict):\n"
            + "    [print(f'{k}={v}') for k, v in result.items()]\n"
        )
        return TrialTemplate(
            command=[sys.executable, "-c", script]
            + [f"{p.name}=${{trialParameters.{p.name}}}" for p in parameters],
            trial_parameters=[
                TrialParameterSpec(name=p.name, reference=p.name) for p in parameters
            ],
            env=dict(env),
        )


# -- sharded control plane routing (ISSUE 15) --------------------------------


class ReplicaRouter:
    """The tiny client-side router of the sharded control plane: reads the
    placement table under ``<root>/placement/`` (controller/placement.py)
    and answers two questions — which replica OWNS an experiment (follow
    its lease), and which replica should receive a NEW one (the live
    replica with the fewest claims). No server round trip: the table is
    plain files on the shared root, exactly what `katib-tpu replicas`
    renders."""

    def __init__(
        self,
        root_dir: str,
        token: Optional[str] = None,
        wire_tracing: Optional[bool] = None,
    ):
        self.root_dir = root_dir
        self.token = token
        # distributed tracing plane (ISSUE 19): None defers to the
        # $KATIB_TPU_WIRE_TRACING env default inside HttpApiClient, so a
        # router in a traced process stamps X-Katib-Traceparent on every
        # routed call without the caller threading the knob explicitly
        self.wire_tracing = wire_tracing

    def table(self) -> Dict[str, Any]:
        from ..controller.placement import placement_table

        return placement_table(self.root_dir)

    def live_replicas(self) -> List[Dict[str, Any]]:
        return [r for r in self.table()["replicas"] if r.get("alive")]

    def owner_url(self, experiment: str) -> Optional[str]:
        """The owning replica's rpc url, or None when unplaced/expired."""
        for row in self.table()["leases"]:
            if (
                row.get("experiment") == experiment
                and row.get("state") == "active"
                and not row.get("expired")
                and row.get("holderAlive")
            ):
                return row.get("url") or None
        return None

    def _persisted_status(self, experiment: str) -> Optional[Dict[str, Any]]:
        """The persisted experiment record from the shared root — the
        authoritative view once the run ended and the placement lease was
        released (completed experiments are unowned by design)."""
        import json as _json

        path = os.path.join(
            self.root_dir, "state", experiment, "state", "experiment.json"
        )
        try:
            with open(path) as f:
                return _json.load(f)
        except (OSError, ValueError):
            return None

    def pick_for_create(self) -> Optional[Dict[str, Any]]:
        live = self.live_replicas()
        if not live:
            return None
        return min(live, key=lambda r: (len(r.get("claimed", [])), r.get("replica", "")))

    # -- remote operations ---------------------------------------------------

    def _client(self, url: str):
        from ..service.httpapi import HttpApiClient

        return HttpApiClient(url, token=self.token, wire_tracing=self.wire_tracing)

    def create_experiment(self, spec_mapping: Dict[str, Any]) -> Dict[str, Any]:
        """Route a spec to the least-loaded live replica; a 429 (capacity)
        falls through to the next candidate."""
        from ..service.httpapi import RpcError

        last: Optional[Exception] = None
        candidates = sorted(
            self.live_replicas(), key=lambda r: (len(r.get("claimed", [])), r.get("replica", ""))
        )
        if not candidates:
            raise RuntimeError(
                f"no live replicas registered under {self.root_dir}/placement"
            )
        for rep in candidates:
            try:
                return self._client(rep["url"]).create_experiment(spec_mapping)
            except RpcError as e:
                if e.code == 429:
                    last = e
                    continue
                raise
        raise RuntimeError(f"every live replica refused the experiment: {last}")

    def experiment_status(self, experiment: str) -> Optional[Dict[str, Any]]:
        """The experiment's status document: the owner's live view while a
        replica holds the placement lease, else the persisted record from
        the shared root (a completed experiment releases its lease, and a
        just-killed owner's experiment is briefly unowned mid-failover)."""
        url = self.owner_url(experiment)
        if url is not None:
            live = self._client(url).experiment_status(experiment)
            if live is not None:
                return live
        return self._persisted_status(experiment)
