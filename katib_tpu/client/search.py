"""Search-space builder helpers for KatibClient.tune.

reference sdk/python/v1beta1/kubeflow/katib/api/search.py:19-64
(katib.search.double/int/categorical returning V1beta1ParameterSpec).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..api.spec import Distribution, FeasibleSpace, ParameterSpec, ParameterType


def double(
    min: float, max: float, step: Optional[float] = None, distribution: Optional[str] = None
) -> ParameterSpec:
    return ParameterSpec(
        name="",
        parameter_type=ParameterType.DOUBLE,
        feasible_space=FeasibleSpace(
            min=str(min),
            max=str(max),
            step=str(step) if step is not None else None,
            distribution=Distribution(distribution) if distribution else None,
        ),
    )


def int_(min: int, max: int, step: Optional[int] = None) -> ParameterSpec:
    return ParameterSpec(
        name="",
        parameter_type=ParameterType.INT,
        feasible_space=FeasibleSpace(
            min=str(min), max=str(max), step=str(step) if step is not None else None
        ),
    )


# the SDK exports this as `int`; keep both spellings
globals()["int"] = int_


def categorical(values: Sequence[Union[str, float, int]]) -> ParameterSpec:
    return ParameterSpec(
        name="",
        parameter_type=ParameterType.CATEGORICAL,
        feasible_space=FeasibleSpace(list=[str(v) for v in values]),
    )
