"""Derived (discretized) DARTS network — retraining the searched genotype.

The reference's DARTS flow stops at the search: the trial image prints
``Best-Genotype`` (examples/v1beta1/trial-images/darts-cnn-cifar10/
run_trial.py:29-259 / model.py genotype()) and retraining the derived
architecture is left to the user. Here the derived network is a first-class
model: ``DerivedNetwork`` instantiates ONLY the genotype's chosen ops (no
mixed-op weighting, no alphas), and ``run_darts_retrain_trial`` is a trial
entry point that consumes a ``genotype`` assignment — so the searched
architecture can itself be trained (or HPO'd over its optimizer settings)
through the same controller.

TPU notes: identical compute idioms to the supernet (MatmulConv im2col
matmuls onto the MXU, one jitted train step, traced optimizer
hyperparameters are unnecessary here since retrain runs once per genotype).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import flax.linen as nn

from ..ops.darts_ops import FactorizedReduce, StdConv, batch_norm, make_op
from ..utils.datasets import batches, load_cifar10

# genotype genes as nested tuples so flax Module fields stay hashable:
# ((("sep_conv_3x3", 0), ("skip_connect", 1)), ...) — one inner tuple per node
Gene = Tuple[Tuple[Tuple[str, int], ...], ...]


def gene_from_json(gene_list) -> Gene:
    """JSON round-trip turns the genotype's (op, edge) tuples into lists;
    normalize back into hashable nested tuples."""
    return tuple(
        tuple((str(op), int(edge)) for op, edge in node) for node in gene_list
    )


class DerivedCell(nn.Module):
    """A supernet Cell with the mixture collapsed to the chosen ops
    (reference model.py Cell at deploy time)."""

    gene: Gene
    channels: int
    reduction_prev: bool
    reduction_cur: bool

    @nn.compact
    def __call__(self, s0, s1):
        if self.reduction_prev:
            s0 = FactorizedReduce(channels=self.channels, name="pre0_reduce")(s0)
        else:
            s0 = StdConv(channels=self.channels, kernel_size=1, name="pre0")(s0)
        s1 = StdConv(channels=self.channels, kernel_size=1, name="pre1")(s1)

        states = [s0, s1]
        for i, node_edges in enumerate(self.gene):
            acc = None
            for op_name, j in node_edges:
                stride = 2 if self.reduction_cur and j < 2 else 1
                out = make_op(op_name, self.channels, stride)(states[j])
                acc = out if acc is None else acc + out
            states.append(acc)
        return jnp.concatenate(states[2:], axis=-1)


class DerivedNetwork(nn.Module):
    """model.py NetworkCNN with the genotype baked in: same stem, same
    reduction schedule, cells built from the discrete genes."""

    normal: Gene
    reduce: Optional[Gene] = None
    init_channels: int = 16
    input_channels: int = 3
    num_classes: int = 10
    num_layers: int = 8
    stem_multiplier: int = 3

    def reduction_layers(self):
        if self.num_layers == 1:
            return []
        if self.num_layers == 2:
            return [1]
        return [self.num_layers // 3, 2 * self.num_layers // 3]

    @nn.compact
    def __call__(self, x):
        from ..ops.darts_ops import MatmulConv

        c_cur = self.stem_multiplier * self.init_channels
        s = MatmulConv(c_cur, (3, 3), name="stem")(x)
        s = batch_norm(s)
        s0 = s1 = s

        reductions = self.reduction_layers()
        c = self.init_channels
        reduction_prev = False
        for layer in range(self.num_layers):
            reduction_cur = layer in reductions
            if reduction_cur:
                c *= 2
            gene = (self.reduce or self.normal) if reduction_cur else self.normal
            cell = DerivedCell(
                gene=gene,
                channels=c,
                reduction_prev=reduction_prev,
                reduction_cur=reduction_cur,
                name=f"cell{layer}",
            )
            s0, s1 = s1, cell(s0, s1)
            reduction_prev = reduction_cur

        out = s1.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, name="classifier")(out)


def run_darts_retrain_trial(assignments: Dict[str, str], ctx=None, **overrides) -> None:
    """Trial entry point: train the architecture a DARTS search produced.

    ``assignments['genotype']`` is the Best-Genotype JSON the search trial
    reported; optimizer settings (lr, momentum, weight_decay, num_epochs,
    batch_size, ...) come from the remaining assignments — making 'retrain
    the winner, HPO its optimizer' a plain experiment over this entry point.
    """
    settings: Dict[str, Any] = dict(assignments)
    settings.update(overrides)
    gene_raw = settings.pop("genotype")
    if isinstance(gene_raw, str):
        # the search prints Best-Genotype as a Python repr (tuples, single
        # quotes); literal_eval parses that directly and also accepts plain
        # JSON (a JSON object without booleans/null is a Python literal)
        import ast

        try:
            gene_raw = ast.literal_eval(gene_raw)
        except (ValueError, SyntaxError):
            gene_raw = json.loads(gene_raw)
    lr = float(settings.get("lr", 0.025))
    momentum = float(settings.get("momentum", 0.9))
    weight_decay = float(settings.get("weight_decay", 3e-4))
    grad_clip = float(settings.get("grad_clip", 5.0))
    num_epochs = int(float(settings.get("num_epochs", 10)))
    batch_size = int(float(settings.get("batch_size", 96)))
    init_channels = int(float(settings.get("init_channels", 16)))
    num_layers = int(float(settings.get("num_layers", 8)))
    stem_multiplier = int(float(settings.get("stem_multiplier", 3)))
    n_train = int(float(settings.get("num_train_examples", 0) or 0)) or None

    model = DerivedNetwork(
        normal=gene_from_json(gene_raw["normal"]),
        reduce=gene_from_json(gene_raw["reduce"]) if gene_raw.get("reduce") else None,
        init_channels=init_channels,
        num_layers=num_layers,
        stem_multiplier=stem_multiplier,
    )

    x, y = load_cifar10("train", n=n_train)
    half = len(x) // 2
    (x_t, y_t), (x_v, y_v) = (x[:half], y[:half]), (x[half:], y[half:])
    steps_per_epoch = max(half // batch_size, 1)

    from ..utils.modelinit import jitted_init

    params = jitted_init(model, jax.random.PRNGKey(0), jnp.zeros((2,) + x.shape[1:]))
    schedule = optax.cosine_decay_schedule(lr, max(steps_per_epoch * num_epochs, 1))
    tx = optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.clip_by_global_norm(grad_clip),
        optax.sgd(schedule, momentum=momentum),
    )
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        bx, by = batch

        def loss_fn(p):
            logits = model.apply({"params": p}, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def evaluate(params, batch):
        bx, by = batch
        logits = model.apply({"params": params}, bx)
        return (jnp.argmax(logits, -1) == by).mean()

    rng = np.random.default_rng(0)
    best_acc = 0.0
    for _epoch in range(num_epochs):
        loss = jnp.float32(0.0)
        for batch in batches(x_t, y_t, min(batch_size, len(x_t)), rng):
            params, opt_state, loss = step(params, opt_state, batch)
        import itertools

        accs = [
            evaluate(params, b)
            for b in itertools.islice(
                batches(x_v, y_v, min(batch_size, len(x_v)), rng), 50
            )
        ]
        acc = float(jnp.stack(accs).mean()) if accs else 0.0
        best_acc = max(best_acc, acc)
        if ctx is not None:
            ctx.report(**{"Validation-accuracy": acc, "Train-loss": float(loss)})
        else:
            print(f"Validation-accuracy={acc}")
            print(f"Train-loss={float(loss)}")
    print(f"Best-accuracy={best_acc}")
