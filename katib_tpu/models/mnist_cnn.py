"""MNIST HPO trial workload — flax re-design of the reference's
pytorch-mnist trial image (examples/v1beta1/trial-images/pytorch-mnist/
mnist.py: conv-conv-fc net, SGD with lr/momentum hyperparameters, prints
per-epoch loss/accuracy for the collector)."""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..utils.datasets import batches, load_mnist


class MnistCNN(nn.Module):
    """mnist.py Net: two convs + two dense layers."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(20, (5, 5))(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(50, (5, 5))(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(500)(x))
        return nn.Dense(10)(x)


def run_mnist_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Entry point: hyperparameters lr / momentum (+ optional batch_size,
    num_epochs, num_train_examples); reports loss and accuracy."""
    lr = float(assignments.get("lr", "0.01"))
    momentum = float(assignments.get("momentum", "0.5"))
    batch_size = int(assignments.get("batch_size", "64"))
    num_epochs = int(assignments.get("num_epochs", "1"))
    n_train = int(assignments.get("num_train_examples", "0")) or None

    x, y = load_mnist("train", n=n_train)
    x_test, y_test = load_mnist("test", n=(n_train // 5 if n_train else None))

    model = MnistCNN()
    from ..utils.modelinit import jitted_init

    params = jitted_init(model, jax.random.PRNGKey(0), jnp.zeros((2,) + x.shape[1:]))
    tx = optax.sgd(lr, momentum=momentum)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, bx, by):
        def loss_fn(p):
            logits = model.apply({"params": p}, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def eval_step(params, bx, by):
        logits = model.apply({"params": params}, bx, train=False)
        return (jnp.argmax(logits, -1) == by).mean()

    from ..utils.prefetch import prefetch_to_device

    rng = np.random.default_rng(0)
    for epoch in range(num_epochs):
        losses = []
        for bx, by in prefetch_to_device(batches(x, y, batch_size, rng)):
            params, opt_state, loss = train_step(params, opt_state, bx, by)
            losses.append(loss)
        accs = [
            eval_step(params, bx, by)
            for bx, by in prefetch_to_device(batches(x_test, y_test, batch_size, rng))
        ]
        if not accs and len(x_test):  # test split smaller than one batch
            accs = [eval_step(params, x_test, y_test)]
        metrics = {
            "loss": float(jnp.stack(losses).mean()) if losses else float("nan"),
            "accuracy": float(jnp.stack(accs).mean()) if accs else 0.0,
        }
        if ctx is not None:
            ctx.report(**metrics)
        else:
            print(f"loss={metrics['loss']}")
            print(f"accuracy={metrics['accuracy']}")
