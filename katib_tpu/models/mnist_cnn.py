"""MNIST HPO trial workload — flax re-design of the reference's
pytorch-mnist trial image (examples/v1beta1/trial-images/pytorch-mnist/
mnist.py: conv-conv-fc net, SGD with lr/momentum hyperparameters, prints
per-epoch loss/accuracy for the collector).

``run_mnist_trial_packed`` is the pack-aware variant (controller/packing.py):
the SAME vectorized code trains a population of K members under ``jax.vmap``
— K > 1 when the scheduler packed compatible trials into one program, K = 1
when a trial runs solo through the normal executor — so packed and
sequential runs execute identical per-member programs."""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..utils.datasets import batches, load_mnist


class MnistCNN(nn.Module):
    """mnist.py Net: two convs + two dense layers. Widths default to the
    reference image's (20/50/500); smaller widths make the "small
    MNIST-CNN" packing benchmark (bench.py pack_throughput)."""

    conv1: int = 20
    conv2: int = 50
    hidden: int = 500

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.conv1, (5, 5))(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(self.conv2, (5, 5))(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(10)(x)


def run_mnist_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Entry point: hyperparameters lr / momentum (+ optional batch_size,
    num_epochs, num_train_examples); reports loss and accuracy."""
    lr = float(assignments.get("lr", "0.01"))
    momentum = float(assignments.get("momentum", "0.5"))
    batch_size = int(assignments.get("batch_size", "64"))
    num_epochs = int(assignments.get("num_epochs", "1"))
    n_train = int(assignments.get("num_train_examples", "0")) or None

    x, y = load_mnist("train", n=n_train)
    x_test, y_test = load_mnist("test", n=(n_train // 5 if n_train else None))

    model = MnistCNN()
    from ..utils.modelinit import jitted_init

    params = jitted_init(model, jax.random.PRNGKey(0), jnp.zeros((2,) + x.shape[1:]))
    tx = optax.sgd(lr, momentum=momentum)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, bx, by):
        def loss_fn(p):
            logits = model.apply({"params": p}, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def eval_step(params, bx, by):
        logits = model.apply({"params": params}, bx, train=False)
        return (jnp.argmax(logits, -1) == by).mean()

    from ..utils.prefetch import prefetch_to_device

    rng = np.random.default_rng(0)
    for epoch in range(num_epochs):
        losses = []
        for bx, by in prefetch_to_device(batches(x, y, batch_size, rng)):
            params, opt_state, loss = train_step(params, opt_state, bx, by)
            losses.append(loss)
        accs = [
            eval_step(params, bx, by)
            for bx, by in prefetch_to_device(batches(x_test, y_test, batch_size, rng))
        ]
        if not accs and len(x_test):  # test split smaller than one batch
            accs = [eval_step(params, x_test, y_test)]
        metrics = {
            "loss": float(jnp.stack(losses).mean()) if losses else float("nan"),
            "accuracy": float(jnp.stack(accs).mean()) if accs else 0.0,
        }
        if ctx is not None:
            ctx.report(**metrics)
        else:
            print(f"loss={metrics['loss']}")
            print(f"accuracy={metrics['accuracy']}")


def run_mnist_trial_packed(assignments, ctx=None) -> None:
    """Pack-aware MNIST trial: a population of K (lr, momentum) members
    trains as ONE ``jax.vmap``-ed program over shared batches — the
    podracer/Anakin batched-learner idiom. Shape-affecting knobs
    (batch_size, num_epochs, num_train_examples) must agree across the pack
    (runtime.packed.uniform_param raises otherwise). Runs unchanged in solo
    mode as a K=1 population."""
    from ..runtime.packed import population_of, report_population, uniform_param

    pop = population_of(assignments)
    packed = ctx is not None and hasattr(ctx, "pack_size")
    k = ctx.pack_size if packed else 1

    batch_size = int(uniform_param(pop, "batch_size", 64))
    num_epochs = int(uniform_param(pop, "num_epochs", 1))
    n_train = int(uniform_param(pop, "num_train_examples", 0)) or None

    lr = jnp.asarray(pop.get("lr", np.full((k,), 0.01, np.float32)))
    momentum = jnp.asarray(pop.get("momentum", np.full((k,), 0.5, np.float32)))

    x, y = load_mnist("train", n=n_train)
    x_test, y_test = load_mnist("test", n=(n_train // 5 if n_train else None))

    model = MnistCNN(
        conv1=int(uniform_param(pop, "conv1_channels", 20)),
        conv2=int(uniform_param(pop, "conv2_channels", 50)),
        hidden=int(uniform_param(pop, "hidden_size", 500)),
    )
    from ..utils.modelinit import jitted_init

    # identical init across members — exactly what each solo trial computes
    params0 = jitted_init(model, jax.random.PRNGKey(0), jnp.zeros((2,) + x.shape[1:]))
    params = jax.tree_util.tree_map(lambda p: jnp.stack([p] * k), params0)
    velocity = jax.tree_util.tree_map(jnp.zeros_like, params)

    def member_step(p, v, lr_i, mom_i, bx, by):
        """SGD-with-momentum (optax.sgd trace semantics, hand-rolled so lr
        and momentum vmap as per-member scalars)."""

        def loss_fn(p):
            logits = model.apply({"params": p}, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(p)
        v = jax.tree_util.tree_map(lambda g, vv: g + mom_i * vv, grads, v)
        p = jax.tree_util.tree_map(lambda pp, vv: pp - lr_i * vv, p, v)
        return p, v, loss

    def masked_step(p, v, lr_, mom_, active, bx, by):
        """One vmapped population step; frozen (early-stopped/killed) members
        keep their state via jnp.where instead of unwinding the pack."""
        p_new, v_new, loss = jax.vmap(
            member_step, in_axes=(0, 0, 0, 0, None, None)
        )(p, v, lr_, mom_, bx, by)

        def keep(new, old):
            mask = active.reshape((-1,) + (1,) * (new.ndim - 1))
            return jnp.where(mask, new, old)

        return (
            jax.tree_util.tree_map(keep, p_new, p),
            jax.tree_util.tree_map(keep, v_new, v),
            loss,
        )

    train_step = jax.jit(masked_step)

    def member_eval(p, bx, by):
        logits = model.apply({"params": p}, bx, train=False)
        return (jnp.argmax(logits, -1) == by).mean()

    eval_step = jax.jit(jax.vmap(member_eval, in_axes=(0, None, None)))

    def active_mask():
        if packed:
            return jnp.asarray(ctx.active_mask)
        return jnp.ones((k,), dtype=bool)

    rng = np.random.default_rng(0)
    for epoch in range(num_epochs):
        losses = []
        for bx, by in batches(x, y, batch_size, rng):
            params, velocity, loss = train_step(
                params, velocity, lr, momentum, active_mask(),
                jnp.asarray(bx), jnp.asarray(by),
            )
            losses.append(loss)
        accs = [
            eval_step(params, jnp.asarray(bx), jnp.asarray(by))
            for bx, by in batches(x_test, y_test, batch_size, rng)
        ]
        if not accs and len(x_test):
            accs = [eval_step(params, jnp.asarray(x_test), jnp.asarray(y_test))]
        loss_pop = (
            jnp.stack(losses).mean(axis=0)
            if losses
            else jnp.full((k,), float("nan"))
        )
        acc_pop = jnp.stack(accs).mean(axis=0) if accs else jnp.zeros((k,))
        report_population(
            ctx, loss=np.asarray(loss_pop), accuracy=np.asarray(acc_pop)
        )


run_mnist_trial_packed.supports_packing = True


def abstract_mnist_program(assignments: Dict[str, str]):
    """Abstract program probe (katib_tpu.analysis.program): the canonical
    jitted train step of the MNIST trial, described with ShapeDtypeStruct
    avals only — eval_shape for the parameter tree, no arrays, no devices.

    lr/momentum enter as traced f32 scalar inputs (runtime-scalar: one
    executable covers the whole sweep); the model widths and batch_size
    select different avals (shape-affecting: one compile per value);
    num_epochs / num_train_examples are host-side loop knobs."""
    from ..analysis.program import ProgramProbe

    batch_size = int(assignments.get("batch_size", "64"))
    model = MnistCNN(
        conv1=int(assignments.get("conv1_channels", "20")),
        conv2=int(assignments.get("conv2_channels", "50")),
        hidden=int(assignments.get("hidden_size", "500")),
    )
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)  # raw PRNG key, abstract
    probe_x = jax.ShapeDtypeStruct((2, 28, 28, 1), jnp.float32)
    params = jax.eval_shape(
        lambda r, x: model.init(r, x)["params"], rng, probe_x
    )
    bx = jax.ShapeDtypeStruct((batch_size, 28, 28, 1), jnp.float32)
    by = jax.ShapeDtypeStruct((batch_size,), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    momentum = jax.ShapeDtypeStruct((), jnp.float32)

    def train_step(params, velocity, lr, momentum, bx, by):
        # SGD-with-momentum with lr/momentum as traced per-call scalars —
        # the same member program run_mnist_trial_packed vmaps (and the
        # shape-bucketed program a shared-executable sweep would compile)
        def loss_fn(p):
            logits = model.apply({"params": p}, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        velocity = jax.tree_util.tree_map(lambda g, v: g + momentum * v, grads, velocity)
        params = jax.tree_util.tree_map(lambda p, v: p - lr * v, params, velocity)
        return params, velocity, loss

    return ProgramProbe(
        fn=train_step,
        args=(params, params, lr, momentum, bx, by),
        params=params,
        hyperparams={"lr": lr, "momentum": momentum},
        host_params={"num_epochs", "num_train_examples"},
    )


run_mnist_trial.abstract_program = abstract_mnist_program
run_mnist_trial_packed.abstract_program = abstract_mnist_program
