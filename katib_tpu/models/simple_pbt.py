"""PBT reference workload — re-design of the reference's simple-pbt trial
image (examples/v1beta1/trial-images/simple-pbt/pbt_test.py:13-127): a
triangle-wave optimal-learning-rate benchmark whose score can only be
maximized by adapting lr over generations, with checkpoint save/restore
through the PBT lineage directory (the suggestion-PVC equivalent,
ctx.checkpoint_dir)."""

from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np

_STEPS_PER_ROUND = 20


def _optimal_lr(step: int, period: int = 100) -> float:
    """Triangle wave in [0, 0.02] (pbt_test.py objective shape)."""
    phase = (step % period) / period
    tri = 2 * phase if phase < 0.5 else 2 * (1 - phase)
    return 0.02 * tri


def run_pbt_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Score improves when lr tracks the moving optimum; state (step, score)
    persists across generations via the checkpoint dir."""
    lr = float(assignments["lr"])

    step, score = 0, 0.0
    ckpt_path = None
    if ctx is not None and ctx.checkpoint_dir:
        os.makedirs(ctx.checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(ctx.checkpoint_dir, "training.json")
        if os.path.exists(ckpt_path):
            with open(ckpt_path) as f:
                state = json.load(f)
            step, score = int(state["step"]), float(state["score"])

    for _ in range(_STEPS_PER_ROUND):
        target = _optimal_lr(step)
        # reward closeness to the optimal lr at this step
        score += max(0.0, 1.0 - abs(lr - target) / 0.02) * 0.01
        step += 1

    if ckpt_path is not None:
        with open(ckpt_path, "w") as f:
            json.dump({"step": step, "score": score}, f)

    if ctx is not None:
        ctx.report(**{"Validation-accuracy": score})
    else:
        print(f"Validation-accuracy={score}")
