"""PBT reference workload — re-design of the reference's simple-pbt trial
image (examples/v1beta1/trial-images/simple-pbt/pbt_test.py:13-127): a
triangle-wave optimal-learning-rate benchmark whose score can only be
maximized by adapting lr over generations, with checkpoint save/restore
through the PBT lineage directory (the suggestion-PVC equivalent,
ctx.checkpoint_dir)."""

from __future__ import annotations

import functools
import json
import os
from typing import Dict

import numpy as np

_STEPS_PER_ROUND = 20
_LR_PERIOD = 100


def _optimal_lr(step: int, period: int = 100) -> float:
    """Triangle wave in [0, 0.02] (pbt_test.py objective shape)."""
    phase = (step % period) / period
    tri = 2 * phase if phase < 0.5 else 2 * (1 - phase)
    return 0.02 * tri


def run_pbt_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Score improves when lr tracks the moving optimum; state (step, score)
    persists across generations via the checkpoint dir."""
    lr = float(assignments["lr"])

    step, score = 0, 0.0
    ckpt_path = None
    if ctx is not None and ctx.checkpoint_dir:
        os.makedirs(ctx.checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(ctx.checkpoint_dir, "training.json")
        if os.path.exists(ckpt_path):
            with open(ckpt_path) as f:
                state = json.load(f)
            step, score = int(state["step"]), float(state["score"])

    for _ in range(_STEPS_PER_ROUND):
        target = _optimal_lr(step)
        # reward closeness to the optimal lr at this step
        score += max(0.0, 1.0 - abs(lr - target) / 0.02) * 0.01
        step += 1

    if ckpt_path is not None:
        # tmp + os.replace: a crash mid-write must leave the previous
        # checkpoint intact, not a truncated JSON the next generation (or a
        # recovery restart) chokes on — the same atomicity every other
        # persistence path in the repo uses (KTI305)
        tmp = ckpt_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "score": score}, f)
        os.replace(tmp, ckpt_path)

    if ctx is not None:
        ctx.report(**{"Validation-accuracy": score})
    else:
        print(f"Validation-accuracy={score}")


def run_pbt_trial_packed(assignments, ctx=None) -> None:
    """Pack-aware PBT workload: one vmapped+jitted program scores a whole
    generation — K members with per-member lr AND per-member checkpoint
    lineage (exploit children start from their parent's step/score). A
    member whose checkpoint is unreadable is failed individually via
    ``ctx.fail_member`` (member failure never fails the pack); the rest of
    the generation keeps training. Runs solo as a K=1 population."""
    import jax
    import jax.numpy as jnp

    from ..runtime.packed import population_of, report_population

    pop = population_of(assignments)
    packed = ctx is not None and hasattr(ctx, "pack_size")
    k = ctx.pack_size if packed else 1
    lr = pop.get("lr")
    if lr is None:
        raise KeyError("lr")

    if packed:
        ckpt_dirs = list(ctx.checkpoint_dirs)
    elif ctx is not None and ctx.checkpoint_dir:
        ckpt_dirs = [ctx.checkpoint_dir]
    else:
        ckpt_dirs = [None] * k

    steps = np.zeros((k,), dtype=np.int32)
    scores = np.zeros((k,), dtype=np.float32)
    ckpt_paths = [None] * k
    for i, d in enumerate(ckpt_dirs):
        if d is None:
            continue
        os.makedirs(d, exist_ok=True)
        ckpt_paths[i] = os.path.join(d, "training.json")
        if not os.path.exists(ckpt_paths[i]):
            continue
        try:
            with open(ckpt_paths[i]) as f:
                state = json.load(f)
            steps[i], scores[i] = int(state["step"]), float(state["score"])
        except (ValueError, KeyError, OSError) as e:
            msg = f"corrupt checkpoint {ckpt_paths[i]}: {e}"
            if packed:
                ctx.fail_member(i, msg)
                ckpt_paths[i] = None  # don't overwrite the evidence
            else:
                raise RuntimeError(msg)

    new_scores = np.asarray(
        _generation_program()(
            jnp.asarray(lr), jnp.asarray(steps, jnp.float32), jnp.asarray(scores)
        )
    )
    new_steps = steps + _STEPS_PER_ROUND

    for i, path in enumerate(ckpt_paths):
        if path is None or (packed and not ctx.member_active(i)):
            continue
        # atomic per-member lineage write (see run_pbt_trial): exploit
        # children copy these files — a torn one would poison the lineage
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": int(new_steps[i]), "score": float(new_scores[i])}, f)
        os.replace(tmp, path)

    report_population(ctx, **{"Validation-accuracy": new_scores})


@functools.lru_cache(maxsize=1)
def _generation_program():
    """The vmapped+jitted generation scorer, built once per process.
    PBT calls run_pbt_trial_packed every generation; a jit wrapper created
    inside it (the pre-ISSUE-6 shape, KTC105) re-traced and re-compiled the
    identical program each time — the exact recompile hazard the analyzer
    exists to catch. With a stable function identity, jit's cache serves
    every generation (one compile per distinct pack size K)."""
    import jax
    import jax.numpy as jnp

    def member_round(lr_i, step0, score0):
        def body(i, score):
            step = step0 + i
            phase = (step % _LR_PERIOD) / _LR_PERIOD
            tri = jnp.where(phase < 0.5, 2.0 * phase, 2.0 * (1.0 - phase))
            target = 0.02 * tri
            return score + jnp.maximum(0.0, 1.0 - jnp.abs(lr_i - target) / 0.02) * 0.01

        return jax.lax.fori_loop(0, _STEPS_PER_ROUND, body, score0)

    return jax.jit(jax.vmap(member_round))


run_pbt_trial_packed.supports_packing = True


def abstract_pbt_program(assignments: Dict[str, str]):
    """Abstract program probe (katib_tpu.analysis.program, ISSUE 9
    satellite): the canonical per-member generation scorer with lr as a
    traced f32 scalar input — the analyzer classifies ``lr`` runtime-scalar
    (one executable covers the whole population) and the PR 8 compile
    service can AOT-prewarm the program at admission instead of raising
    KTX404."""
    import jax
    import jax.numpy as jnp

    from ..analysis.program import ProgramProbe

    lr = jax.ShapeDtypeStruct((), jnp.float32)
    step0 = jax.ShapeDtypeStruct((), jnp.float32)
    score0 = jax.ShapeDtypeStruct((), jnp.float32)

    def member_round(lr, step0, score0):
        def body(i, score):
            step = step0 + i
            phase = (step % _LR_PERIOD) / _LR_PERIOD
            tri = jnp.where(phase < 0.5, 2.0 * phase, 2.0 * (1.0 - phase))
            target = 0.02 * tri
            return score + jnp.maximum(0.0, 1.0 - jnp.abs(lr - target) / 0.02) * 0.01

        return jax.lax.fori_loop(0, _STEPS_PER_ROUND, body, score0)

    return ProgramProbe(
        fn=member_round,
        args=(lr, step0, score0),
        hyperparams={"lr": lr},
    )


run_pbt_trial.abstract_program = abstract_pbt_program
run_pbt_trial_packed.abstract_program = abstract_pbt_program


def pbt_population_program(spec):
    """Fused population probe (katib_tpu.runtime.population): the whole
    triangle-wave PBT benchmark as ONE generation step — the per-member
    fori_loop scorer vmapped over the population, truncation
    exploit/explore selection fused behind it — run as a single
    ``lax.scan`` program per sweep instead of one job-queue round-trip per
    generation. Member state (step, score) is the checkpoint lineage the
    job-queue driver keeps in ``training.json``; exploit copies a top
    performer's lr AND its accumulated state, exactly the lineage-copy
    semantics of the suggestion-PVC ``shutil.copytree``."""
    import jax
    import jax.numpy as jnp

    from ..runtime import population as pop
    from ..suggest.internal.search_space import MIN_GOAL, SearchSpace

    space = SearchSpace.from_experiment(spec)
    settings = spec.algorithm.settings_dict()
    numeric = [p for p in space.params if p.is_numeric]
    if not numeric:
        raise ValueError("simple_pbt fused program needs numeric parameters")
    names = [p.name for p in numeric]
    lower = [p.min for p in numeric]
    upper = [p.max for p in numeric]
    # the suggest/pbt.py _Sampler grid: explicit step, else span/100
    grid = [
        p.step if p.step else ((p.max - p.min) / 100.0 or 1.0) for p in numeric
    ]
    lr_col = names.index("lr") if "lr" in names else 0

    def init_member(key, hp_row):
        del key, hp_row
        return {
            "step": jnp.zeros((), jnp.float32),
            "score": jnp.zeros((), jnp.float32),
        }

    def member_step(state, hp_row, key):
        del key
        lr = hp_row[lr_col]
        step0 = state["step"]

        def body(i, score):
            step = step0 + i
            phase = (step % _LR_PERIOD) / _LR_PERIOD
            tri = jnp.where(phase < 0.5, 2.0 * phase, 2.0 * (1.0 - phase))
            target = 0.02 * tri
            return score + jnp.maximum(0.0, 1.0 - jnp.abs(lr - target) / 0.02) * 0.01

        score = jax.lax.fori_loop(0, _STEPS_PER_ROUND, body, state["score"])
        return {"step": step0 + _STEPS_PER_ROUND, "score": score}, score

    resample = settings.get("resample_probability")
    seed = int(settings.get("random_state", "0") or 0)
    return pop.pbt_program(
        name="katib_tpu.models.simple_pbt:run_pbt_trial_packed",
        metric=spec.objective.objective_metric_name or "Validation-accuracy",
        n_population=int(settings.get("n_population", "8")),
        hyperparams=names,
        lower=lower,
        upper=upper,
        grid_step=grid,
        truncation=float(settings.get("truncation_threshold", "0.2")),
        resample_probability=float(resample) if resample is not None else None,
        goal_scale=-1.0 if space.goal == MIN_GOAL else 1.0,
        init_member=init_member,
        member_step=member_step,
        seed=seed,
    )


run_pbt_trial.population_program = pbt_population_program
run_pbt_trial_packed.population_program = pbt_population_program
