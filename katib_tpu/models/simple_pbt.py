"""PBT reference workload — re-design of the reference's simple-pbt trial
image (examples/v1beta1/trial-images/simple-pbt/pbt_test.py:13-127): a
triangle-wave optimal-learning-rate benchmark whose score can only be
maximized by adapting lr over generations, with checkpoint save/restore
through the PBT lineage directory (the suggestion-PVC equivalent,
ctx.checkpoint_dir)."""

from __future__ import annotations

import functools
import json
import os
from typing import Dict

import numpy as np

_STEPS_PER_ROUND = 20
_LR_PERIOD = 100


def _optimal_lr(step: int, period: int = 100) -> float:
    """Triangle wave in [0, 0.02] (pbt_test.py objective shape)."""
    phase = (step % period) / period
    tri = 2 * phase if phase < 0.5 else 2 * (1 - phase)
    return 0.02 * tri


def run_pbt_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Score improves when lr tracks the moving optimum; state (step, score)
    persists across generations via the checkpoint dir."""
    lr = float(assignments["lr"])

    step, score = 0, 0.0
    ckpt_path = None
    if ctx is not None and ctx.checkpoint_dir:
        os.makedirs(ctx.checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(ctx.checkpoint_dir, "training.json")
        if os.path.exists(ckpt_path):
            with open(ckpt_path) as f:
                state = json.load(f)
            step, score = int(state["step"]), float(state["score"])

    for _ in range(_STEPS_PER_ROUND):
        target = _optimal_lr(step)
        # reward closeness to the optimal lr at this step
        score += max(0.0, 1.0 - abs(lr - target) / 0.02) * 0.01
        step += 1

    if ckpt_path is not None:
        with open(ckpt_path, "w") as f:
            json.dump({"step": step, "score": score}, f)

    if ctx is not None:
        ctx.report(**{"Validation-accuracy": score})
    else:
        print(f"Validation-accuracy={score}")


def run_pbt_trial_packed(assignments, ctx=None) -> None:
    """Pack-aware PBT workload: one vmapped+jitted program scores a whole
    generation — K members with per-member lr AND per-member checkpoint
    lineage (exploit children start from their parent's step/score). A
    member whose checkpoint is unreadable is failed individually via
    ``ctx.fail_member`` (member failure never fails the pack); the rest of
    the generation keeps training. Runs solo as a K=1 population."""
    import jax
    import jax.numpy as jnp

    from ..runtime.packed import population_of, report_population

    pop = population_of(assignments)
    packed = ctx is not None and hasattr(ctx, "pack_size")
    k = ctx.pack_size if packed else 1
    lr = pop.get("lr")
    if lr is None:
        raise KeyError("lr")

    if packed:
        ckpt_dirs = list(ctx.checkpoint_dirs)
    elif ctx is not None and ctx.checkpoint_dir:
        ckpt_dirs = [ctx.checkpoint_dir]
    else:
        ckpt_dirs = [None] * k

    steps = np.zeros((k,), dtype=np.int32)
    scores = np.zeros((k,), dtype=np.float32)
    ckpt_paths = [None] * k
    for i, d in enumerate(ckpt_dirs):
        if d is None:
            continue
        os.makedirs(d, exist_ok=True)
        ckpt_paths[i] = os.path.join(d, "training.json")
        if not os.path.exists(ckpt_paths[i]):
            continue
        try:
            with open(ckpt_paths[i]) as f:
                state = json.load(f)
            steps[i], scores[i] = int(state["step"]), float(state["score"])
        except (ValueError, KeyError, OSError) as e:
            msg = f"corrupt checkpoint {ckpt_paths[i]}: {e}"
            if packed:
                ctx.fail_member(i, msg)
                ckpt_paths[i] = None  # don't overwrite the evidence
            else:
                raise RuntimeError(msg)

    new_scores = np.asarray(
        _generation_program()(
            jnp.asarray(lr), jnp.asarray(steps, jnp.float32), jnp.asarray(scores)
        )
    )
    new_steps = steps + _STEPS_PER_ROUND

    for i, path in enumerate(ckpt_paths):
        if path is None or (packed and not ctx.member_active(i)):
            continue
        with open(path, "w") as f:
            json.dump({"step": int(new_steps[i]), "score": float(new_scores[i])}, f)

    report_population(ctx, **{"Validation-accuracy": new_scores})


@functools.lru_cache(maxsize=1)
def _generation_program():
    """The vmapped+jitted generation scorer, built once per process.
    PBT calls run_pbt_trial_packed every generation; a jit wrapper created
    inside it (the pre-ISSUE-6 shape, KTC105) re-traced and re-compiled the
    identical program each time — the exact recompile hazard the analyzer
    exists to catch. With a stable function identity, jit's cache serves
    every generation (one compile per distinct pack size K)."""
    import jax
    import jax.numpy as jnp

    def member_round(lr_i, step0, score0):
        def body(i, score):
            step = step0 + i
            phase = (step % _LR_PERIOD) / _LR_PERIOD
            tri = jnp.where(phase < 0.5, 2.0 * phase, 2.0 * (1.0 - phase))
            target = 0.02 * tri
            return score + jnp.maximum(0.0, 1.0 - jnp.abs(lr_i - target) / 0.02) * 0.01

        return jax.lax.fori_loop(0, _STEPS_PER_ROUND, body, score0)

    return jax.jit(jax.vmap(member_round))


run_pbt_trial_packed.supports_packing = True
