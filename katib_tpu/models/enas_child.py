"""ENAS child network builder + trainer — TPU re-design of the reference's
enas-cnn-cifar10 trial image.

reference examples/v1beta1/trial-images/enas-cnn-cifar10/{ModelConstructor.py,
op_library.py, RunTrial.py}: decodes the controller-emitted ``architecture``
(per-layer [op, skip bits...]) and ``nn_config`` (embedding of concrete
operations) into a CNN:

- layer l concatenates the previous layer with all skip-connected earlier
  layers (spatially zero-padded to the largest H/W) and applies its op;
- ops: convolution, separable_convolution, depthwise_convolution, reduction
  (max/avg pool; identity when the spatial dim is already 1);
- head: global average pool -> dropout(0.4) -> dense softmax.

Re-design notes: flax module built dynamically from the arch (static under
jit — each architecture compiles once); train-mode stateless batch norm like
the DARTS ops; NHWC.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..ops.darts_ops import batch_norm
from ..utils.datasets import batches, load_dataset


def _pad_to(x: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """op_library.py concat: zero-pad spatial dims up to (h, w)."""
    dh, dw = h - x.shape[1], w - x.shape[2]
    if dh == 0 and dw == 0:
        return x
    top, left = dh // 2, dw // 2
    return jnp.pad(x, ((0, 0), (top, dh - top), (left, dw - left), (0, 0)))


def _concat_inputs(inputs: List[jnp.ndarray]) -> jnp.ndarray:
    if len(inputs) == 1:
        return inputs[0]
    h = max(i.shape[1] for i in inputs)
    w = max(i.shape[2] for i in inputs)
    return jnp.concatenate([_pad_to(i, h, w) for i in inputs], axis=-1)


class EnasChildNet(nn.Module):
    """ModelConstructor.build_model as a flax module."""

    arch: Any            # list of [op, skip...] per layer (parsed)
    embedding: Dict[str, Dict[str, Any]]
    num_classes: int = 10
    dropout_rate: float = 0.4

    @nn.compact
    def __call__(self, x, train: bool = True):
        layers = [x]
        num_layers = len(self.arch)
        for l in range(1, num_layers + 1):
            opt = self.arch[l - 1][0]
            skip = self.arch[l - 1][1 : l + 1]
            cfg = self.embedding[str(opt)]
            params = cfg.get("opt_params", {})
            inputs = [layers[l - 1]]
            for i in range(l - 1):
                if l > 1 and i < len(skip) and skip[i] == 1:
                    inputs.append(layers[i])
            h = _concat_inputs(inputs)
            h = self._apply_op(h, cfg["opt_type"], params, name=f"layer{l}")
            layers.append(h)

        out = layers[-1].mean(axis=(1, 2))
        out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        return nn.Dense(self.num_classes, name="classifier")(out)

    def _apply_op(self, x, opt_type: str, p: Dict[str, Any], name: str):
        num_filter = int(p.get("num_filter", 64))
        filter_size = int(p.get("filter_size", 3))
        stride = int(p.get("stride", 1) or 1)
        if opt_type == "convolution":
            x = nn.relu(x)
            x = nn.Conv(
                num_filter, (filter_size, filter_size), strides=(stride, stride),
                padding="SAME", name=f"{name}_conv",
            )(x)
            return batch_norm(x)
        if opt_type == "separable_convolution":
            depth_mult = int(p.get("depth_multiplier", 1))
            x = nn.relu(x)
            x = nn.Conv(
                x.shape[-1] * depth_mult, (filter_size, filter_size),
                strides=(stride, stride), padding="SAME",
                feature_group_count=x.shape[-1], name=f"{name}_dw",
            )(x)
            x = nn.Conv(num_filter, (1, 1), name=f"{name}_pw")(x)
            return batch_norm(x)
        if opt_type == "depthwise_convolution":
            depth_mult = int(p.get("depth_multiplier", 1))
            x = nn.relu(x)
            x = nn.Conv(
                x.shape[-1] * depth_mult, (filter_size, filter_size),
                strides=(stride, stride), padding="SAME",
                feature_group_count=x.shape[-1], name=f"{name}_dw",
            )(x)
            return batch_norm(x)
        if opt_type == "reduction":
            if x.shape[1] == 1 or x.shape[2] == 1:
                return x  # identity fallback (op_library.py reduction)
            pool = int(p.get("pool_size", 2))
            stride_p = p.get("stride") or pool
            stride_p = int(stride_p)
            rtype = p.get("reduction_type", "max_pooling")
            if rtype == "avg_pooling":
                return nn.avg_pool(x, (pool, pool), strides=(stride_p, stride_p))
            return nn.max_pool(x, (pool, pool), strides=(stride_p, stride_p))
        raise ValueError(f"unknown ENAS op type {opt_type!r}")


def run_enas_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Trial entry point — RunTrial.py equivalent: decode architecture, train,
    report per-epoch Validation-accuracy (latest strategy)."""
    arch = json.loads(assignments["architecture"].replace("'", '"'))
    nn_config = json.loads(assignments["nn_config"].replace("'", '"'))
    num_epochs = int(assignments.get("num_epochs", "3"))
    batch_size = int(assignments.get("batch_size", "128"))
    lr = float(assignments.get("learning_rate", "0.002"))
    n_train = int(assignments.get("num_train_examples", "0")) or None

    num_classes = int(nn_config["output_sizes"][-1])
    model = EnasChildNet(
        arch=tuple(tuple(l) for l in arch),
        embedding=nn_config["embedding"],
        num_classes=num_classes,
    )

    # dataset knob: "digits" routes to the REAL bundled UCI handwritten
    # digits (upsampled to the graph's 32x32x3 stem) so NAS records can run
    # on genuine pixels in this zero-egress environment; default stays the
    # CIFAR-10 loader (real npz when present, synthetic stand-in otherwise).
    x, y = load_dataset(assignments.get("dataset", "cifar"), "train", n=n_train)
    split = int(len(x) * 0.9)
    x_t, y_t, x_v, y_v = x[:split], y[:split], x[split:], y[split:]

    from ..utils.modelinit import jitted_init

    key = jax.random.PRNGKey(0)
    params = jitted_init(
        model, {"params": key, "dropout": key}, jnp.zeros((2,) + x.shape[1:])
    )
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    # Data-parallel child training over the trial's gang-allocated devices
    # (same contract as run_darts_hpo_trial): params/optimizer replicate,
    # batches shard over 'data', GSPMD all-reduces the grads. Only engaged
    # when the fixed batch size divides the device count so every jitted
    # shape stays static.
    batch_sharding = replicated = None
    devices = ctx.jax_devices() if ctx is not None else []
    if len(devices) > 1:
        if batch_size % len(devices) == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = ctx.mesh(axis_names=("data",))
            replicated = NamedSharding(mesh, P())
            batch_sharding = NamedSharding(mesh, P("data"))
            params, opt_state = jax.device_put((params, opt_state), replicated)
        else:
            # visible, not silent: the gang allocated chips this trial
            # can't use at this batch size
            print(
                f"enas-child: batch_size {batch_size} not divisible by "
                f"{len(devices)} gang devices; training single-device",
                flush=True,
            )

    @jax.jit
    def train_step(params, opt_state, key, bx, by):
        def loss_fn(p):
            logits = model.apply({"params": p}, bx, train=True, rngs={"dropout": key})
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def eval_step(params, bx, by):
        logits = model.apply({"params": params}, bx, train=False)
        return (jnp.argmax(logits, -1) == by).mean()

    from ..utils.prefetch import prefetch_to_device

    rng = np.random.default_rng(0)
    loss = jnp.array(float("nan"))
    for epoch in range(num_epochs):
        whole_set = len(x_t) < batch_size
        train_iter = prefetch_to_device(
            [(x_t, y_t)] if whole_set else batches(x_t, y_t, batch_size, rng),
            # the whole-set fallback has an arbitrary length: keep it
            # replicated (params already are) instead of risking a ragged
            # 'data' split
            sharding=replicated if whole_set else batch_sharding,
        )
        for bx, by in train_iter:
            key, sub = jax.random.split(key)
            params, opt_state, loss = train_step(params, opt_state, sub, bx, by)
        accs = [
            eval_step(params, bx, by)
            for bx, by in prefetch_to_device(
                batches(x_v, y_v, batch_size, rng), sharding=batch_sharding
            )
        ]
        if not accs and len(x_v):  # val split smaller than one batch
            x_vd, y_vd = (
                jax.device_put((x_v, y_v), replicated)
                if replicated is not None
                else (x_v, y_v)
            )
            accs = [eval_step(params, x_vd, y_vd)]
        acc = float(jnp.stack(accs).mean()) if accs else 0.0
        if ctx is not None:
            ctx.report(**{"Validation-accuracy": acc, "Train-loss": float(loss)})
        else:
            print(f"Epoch {epoch+1}:")
            print(f"Validation-accuracy={acc}")
            print(f"Train-loss={float(loss)}")
