"""ENAS child network builder + trainer — TPU re-design of the reference's
enas-cnn-cifar10 trial image.

reference examples/v1beta1/trial-images/enas-cnn-cifar10/{ModelConstructor.py,
op_library.py, RunTrial.py}: decodes the controller-emitted ``architecture``
(per-layer [op, skip bits...]) and ``nn_config`` (embedding of concrete
operations) into a CNN:

- layer l concatenates the previous layer with all skip-connected earlier
  layers (spatially zero-padded to the largest H/W) and applies its op;
- ops: convolution, separable_convolution, depthwise_convolution, reduction
  (max/avg pool; identity when the spatial dim is already 1);
- head: global average pool -> dropout(0.4) -> dense softmax.

Re-design notes: flax module built dynamically from the arch (static under
jit — each architecture compiles once); train-mode stateless batch norm like
the DARTS ops; NHWC.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..ops.darts_ops import batch_norm
from ..utils.datasets import batches, load_dataset


def _pad_to(x: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """op_library.py concat: zero-pad spatial dims up to (h, w)."""
    dh, dw = h - x.shape[1], w - x.shape[2]
    if dh == 0 and dw == 0:
        return x
    top, left = dh // 2, dw // 2
    return jnp.pad(x, ((0, 0), (top, dh - top), (left, dw - left), (0, 0)))


def _concat_inputs(inputs: List[jnp.ndarray]) -> jnp.ndarray:
    if len(inputs) == 1:
        return inputs[0]
    h = max(i.shape[1] for i in inputs)
    w = max(i.shape[2] for i in inputs)
    return jnp.concatenate([_pad_to(i, h, w) for i in inputs], axis=-1)


class EnasChildNet(nn.Module):
    """ModelConstructor.build_model as a flax module."""

    arch: Any            # list of [op, skip...] per layer (parsed)
    embedding: Dict[str, Dict[str, Any]]
    num_classes: int = 10
    dropout_rate: float = 0.4

    @nn.compact
    def __call__(self, x, train: bool = True):
        layers = [x]
        num_layers = len(self.arch)
        for l in range(1, num_layers + 1):
            opt = self.arch[l - 1][0]
            skip = self.arch[l - 1][1 : l + 1]
            cfg = self.embedding[str(opt)]
            params = cfg.get("opt_params", {})
            inputs = [layers[l - 1]]
            for i in range(l - 1):
                if l > 1 and i < len(skip) and skip[i] == 1:
                    inputs.append(layers[i])
            h = _concat_inputs(inputs)
            h = self._apply_op(h, cfg["opt_type"], params, name=f"layer{l}")
            layers.append(h)

        out = layers[-1].mean(axis=(1, 2))
        out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        return nn.Dense(self.num_classes, name="classifier")(out)

    def _apply_op(self, x, opt_type: str, p: Dict[str, Any], name: str):
        num_filter = int(p.get("num_filter", 64))
        filter_size = int(p.get("filter_size", 3))
        stride = int(p.get("stride", 1) or 1)
        if opt_type == "convolution":
            x = nn.relu(x)
            x = nn.Conv(
                num_filter, (filter_size, filter_size), strides=(stride, stride),
                padding="SAME", name=f"{name}_conv",
            )(x)
            return batch_norm(x)
        if opt_type == "separable_convolution":
            depth_mult = int(p.get("depth_multiplier", 1))
            x = nn.relu(x)
            x = nn.Conv(
                x.shape[-1] * depth_mult, (filter_size, filter_size),
                strides=(stride, stride), padding="SAME",
                feature_group_count=x.shape[-1], name=f"{name}_dw",
            )(x)
            x = nn.Conv(num_filter, (1, 1), name=f"{name}_pw")(x)
            return batch_norm(x)
        if opt_type == "depthwise_convolution":
            depth_mult = int(p.get("depth_multiplier", 1))
            x = nn.relu(x)
            x = nn.Conv(
                x.shape[-1] * depth_mult, (filter_size, filter_size),
                strides=(stride, stride), padding="SAME",
                feature_group_count=x.shape[-1], name=f"{name}_dw",
            )(x)
            return batch_norm(x)
        if opt_type == "reduction":
            if x.shape[1] == 1 or x.shape[2] == 1:
                return x  # identity fallback (op_library.py reduction)
            pool = int(p.get("pool_size", 2))
            stride_p = p.get("stride") or pool
            stride_p = int(stride_p)
            rtype = p.get("reduction_type", "max_pooling")
            if rtype == "avg_pooling":
                return nn.avg_pool(x, (pool, pool), strides=(stride_p, stride_p))
            return nn.max_pool(x, (pool, pool), strides=(stride_p, stride_p))
        raise ValueError(f"unknown ENAS op type {opt_type!r}")


def run_enas_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Trial entry point — RunTrial.py equivalent: decode architecture, train,
    report per-epoch Validation-accuracy (latest strategy)."""
    arch = json.loads(assignments["architecture"].replace("'", '"'))
    nn_config = json.loads(assignments["nn_config"].replace("'", '"'))
    num_epochs = int(assignments.get("num_epochs", "3"))
    batch_size = int(assignments.get("batch_size", "128"))
    lr = float(assignments.get("learning_rate", "0.002"))
    n_train = int(assignments.get("num_train_examples", "0")) or None

    num_classes = int(nn_config["output_sizes"][-1])
    model = EnasChildNet(
        arch=tuple(tuple(l) for l in arch),
        embedding=nn_config["embedding"],
        num_classes=num_classes,
    )

    # dataset knob: "digits" routes to the REAL bundled UCI handwritten
    # digits (upsampled to the graph's 32x32x3 stem) so NAS records can run
    # on genuine pixels in this zero-egress environment; default stays the
    # CIFAR-10 loader (real npz when present, synthetic stand-in otherwise).
    x, y = load_dataset(assignments.get("dataset", "cifar"), "train", n=n_train)
    split = int(len(x) * 0.9)
    x_t, y_t, x_v, y_v = x[:split], y[:split], x[split:], y[split:]

    from ..utils.modelinit import jitted_init

    key = jax.random.PRNGKey(0)
    params = jitted_init(
        model, {"params": key, "dropout": key}, jnp.zeros((2,) + x.shape[1:])
    )
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    # Data-parallel child training over the trial's gang-allocated devices
    # (same contract as run_darts_hpo_trial): params/optimizer replicate,
    # batches shard over 'data', GSPMD all-reduces the grads. Only engaged
    # when the fixed batch size divides the device count so every jitted
    # shape stays static.
    batch_sharding = replicated = None
    devices = ctx.jax_devices() if ctx is not None else []
    if len(devices) > 1:
        if batch_size % len(devices) == 0:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = ctx.mesh(axis_names=("data",))
            replicated = NamedSharding(mesh, P())
            batch_sharding = NamedSharding(mesh, P("data"))
            params, opt_state = jax.device_put((params, opt_state), replicated)
        else:
            # visible, not silent: the gang allocated chips this trial
            # can't use at this batch size
            print(
                f"enas-child: batch_size {batch_size} not divisible by "
                f"{len(devices)} gang devices; training single-device",
                flush=True,
            )

    @jax.jit
    def train_step(params, opt_state, key, bx, by):
        def loss_fn(p):
            logits = model.apply({"params": p}, bx, train=True, rngs={"dropout": key})
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def eval_step(params, bx, by):
        logits = model.apply({"params": params}, bx, train=False)
        return (jnp.argmax(logits, -1) == by).mean()

    from ..utils.prefetch import prefetch_to_device

    rng = np.random.default_rng(0)
    loss = jnp.array(float("nan"))
    for epoch in range(num_epochs):
        whole_set = len(x_t) < batch_size
        train_iter = prefetch_to_device(
            [(x_t, y_t)] if whole_set else batches(x_t, y_t, batch_size, rng),
            # the whole-set fallback has an arbitrary length: keep it
            # replicated (params already are) instead of risking a ragged
            # 'data' split
            sharding=replicated if whole_set else batch_sharding,
        )
        for bx, by in train_iter:
            key, sub = jax.random.split(key)
            params, opt_state, loss = train_step(params, opt_state, sub, bx, by)
        accs = [
            eval_step(params, bx, by)
            for bx, by in prefetch_to_device(
                batches(x_v, y_v, batch_size, rng), sharding=batch_sharding
            )
        ]
        if not accs and len(x_v):  # val split smaller than one batch
            x_vd, y_vd = (
                jax.device_put((x_v, y_v), replicated)
                if replicated is not None
                else (x_v, y_v)
            )
            accs = [eval_step(params, x_vd, y_vd)]
        acc = float(jnp.stack(accs).mean()) if accs else 0.0
        if ctx is not None:
            ctx.report(**{"Validation-accuracy": acc, "Train-loss": float(loss)})
        else:
            print(f"Epoch {epoch+1}:")
            print(f"Validation-accuracy={acc}")
            print(f"Train-loss={float(loss)}")


# ---------------------------------------------------------------------------
# Fused population probes (ISSUE 9): ENAS as one compiled generation program
# ---------------------------------------------------------------------------

def abstract_enas_child_program(assignments: Dict[str, str]):
    """Abstract program probe (katib_tpu.analysis.program): the canonical
    jitted child train step under a default (or assignment-supplied)
    architecture, with learning_rate as a traced f32 scalar input — the
    analyzer classifies the ENAS child instead of raising KTX404, and the
    compile service can prewarm the child program at admission."""
    from ..analysis.program import ProgramProbe

    if "architecture" in assignments and "nn_config" in assignments:
        arch = json.loads(assignments["architecture"].replace("'", '"'))
        nn_config = json.loads(assignments["nn_config"].replace("'", '"'))
        embedding = nn_config["embedding"]
        num_classes = int(nn_config["output_sizes"][-1])
    else:
        # probe-default architecture: 2 conv layers, one skip bit
        arch = [[0], [0, 1]]
        embedding = {
            "0": {
                "opt_id": 0,
                "opt_type": "convolution",
                "opt_params": {"num_filter": 8, "filter_size": 3},
            }
        }
        num_classes = 10
    batch_size = int(assignments.get("batch_size", "8"))
    model = EnasChildNet(
        arch=tuple(tuple(l) for l in arch),
        embedding=embedding,
        num_classes=num_classes,
    )
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    probe_x = jax.ShapeDtypeStruct((2, 32, 32, 3), jnp.float32)
    params = jax.eval_shape(
        lambda r, x: model.init(
            {"params": r, "dropout": r}, x, train=True
        )["params"],
        rng, probe_x,
    )
    bx = jax.ShapeDtypeStruct((batch_size, 32, 32, 3), jnp.float32)
    by = jax.ShapeDtypeStruct((batch_size,), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)

    def train_step(params, lr, key, bx, by):
        def loss_fn(p):
            logits = model.apply(
                {"params": p}, bx, train=True, rngs={"dropout": key}
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, by
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return ProgramProbe(
        fn=train_step,
        args=(params, lr, rng, bx, by),
        params=params,
        hyperparams={"learning_rate": lr},
        host_params={"num_epochs", "num_train_examples", "dataset"},
    )


run_enas_trial.abstract_program = abstract_enas_child_program


def _supernet_init(key, num_layers: int, op_kernels, in_ch: int,
                   channels: int, num_classes: int):
    """Shared-supernet parameters: one stem conv, per-(layer, op) kernels
    for the conv-family ops (pool ops are parameterless), one classifier."""
    n_params = 2 + sum(1 for ks in op_kernels if ks is not None) * num_layers
    keys = jax.random.split(key, n_params)
    it = iter(keys)

    def conv_init(k, kh, kw, cin, cout):
        scale = 1.0 / np.sqrt(kh * kw * cin)
        return jax.random.uniform(
            k, (kh, kw, cin, cout), minval=-scale, maxval=scale
        )

    params = {"stem": conv_init(next(it), 3, 3, in_ch, channels)}
    for l in range(num_layers):
        layer = {}
        for o, ks in enumerate(op_kernels):
            if ks is not None:
                layer[f"op{o}"] = conv_init(next(it), ks, ks, channels, channels)
        params[f"layer{l}"] = layer
    params["head"] = conv_init(next(it), 1, 1, channels, num_classes)
    return params


def _supernet_apply(params, x, arc_flat, num_layers: int, op_kinds):
    """Forward one architecture through the shared supernet: every op
    branch is computed and the sampled op selected via one-hot mixing
    (jnp.where-style traceable selection — the weight-sharing trick that
    makes ENAS architectures indexable instead of rebuilt per sample), and
    skip bits gate additive connections to earlier layers."""
    h = jax.lax.conv_general_dilated(
        x, params["stem"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    outs = [h]
    offset = 0
    num_ops = len(op_kinds)
    for l in range(num_layers):
        op_id = arc_flat[offset]
        skips = arc_flat[offset + 1: offset + 1 + l]
        offset += 1 + l
        inp = outs[-1]
        if l > 0:
            gates = skips.astype(jnp.float32)
            mixed = inp
            for i in range(l):
                mixed = mixed + gates[i] * outs[i]
            inp = mixed / (1.0 + gates.sum())
        branches = []
        layer_params = params[f"layer{l}"]
        for o, kind in enumerate(op_kinds):
            if kind == "pool_avg":
                branches.append(
                    jax.lax.reduce_window(
                        inp, 0.0, jax.lax.add, (1, 2, 2, 1), (1, 1, 1, 1),
                        "SAME",
                    ) / 4.0
                )
            elif kind == "pool_max":
                branches.append(
                    jax.lax.reduce_window(
                        inp, -jnp.inf, jax.lax.max, (1, 2, 2, 1),
                        (1, 1, 1, 1), "SAME",
                    )
                )
            else:
                branches.append(
                    jax.nn.relu(
                        jax.lax.conv_general_dilated(
                            inp, layer_params[f"op{o}"], (1, 1), "SAME",
                            dimension_numbers=("NHWC", "HWIO", "NHWC"),
                        )
                    )
                )
        stacked = jnp.stack(branches)           # [O, N, H, W, C]
        onehot = jax.nn.one_hot(op_id, num_ops) # traced op selection
        h = jnp.einsum("o,onhwc->nhwc", onehot, stacked)
        outs.append(h)
    pooled = outs[-1].mean(axis=(1, 2))         # global average pool
    logits = jnp.einsum(
        "nc,cd->nd", pooled, params["head"][0, 0]
    )
    return logits


def _op_kind(cfg: Dict[str, Any]):
    """Map one expanded NAS operation onto the supernet op bank: conv-family
    ops keep a per-(layer, op) kernel of their configured size; reductions
    become shape-preserving pools (stride-1 SAME — the weight-sharing
    surrogate of the pooling op)."""
    t = cfg.get("opt_type", "convolution")
    if t == "reduction":
        if cfg.get("opt_params", {}).get("reduction_type") == "avg_pooling":
            return "pool_avg", None
        return "pool_max", None
    size = int(cfg.get("opt_params", {}).get("filter_size", 3))
    return "conv", size


def enas_population_program(spec):
    """Fused population probe (katib_tpu.runtime.population): the whole
    ENAS search — controller-LSTM sampling, weight-shared child
    train/eval, REINFORCE update — as one generation step run under
    ``lax.scan``. The child is the shared supernet above trained on the
    real bundled digits set (a small slice, so a CPU test sweep stays
    fast); settings ``fused_child_examples`` / ``fused_child_batch`` /
    ``fused_child_steps`` size it."""
    from ..runtime import population as pop
    from ..suggest.nas.enas import expand_operations, parse_enas_settings
    from ..utils.datasets import load_dataset

    settings = parse_enas_settings(spec)
    raw = spec.algorithm.settings_dict()
    nas = spec.nas_config
    num_layers = int(nas.graph_config.num_layers)
    ops = expand_operations(nas)
    kinds, sizes = [], []
    for cfg in ops:
        kind, size = _op_kind(cfg)
        kinds.append(kind)
        sizes.append(size)
    op_kernels = [s for s in sizes]
    num_classes = int(nas.graph_config.output_sizes[-1])
    channels = int(raw.get("fused_child_channels", "8"))
    n_examples = int(raw.get("fused_child_examples", "192"))
    batch = int(raw.get("fused_child_batch", "32"))
    train_steps = int(raw.get("fused_child_steps", "1"))
    k_pop = int(raw.get("n_population", raw.get("fused_population_size", "8")))
    lr = float(raw.get("fused_child_lr", "0.05"))

    x, y = load_dataset("digits", "train", n=n_examples)
    split = max(int(len(x) * 0.75), 1)
    x_t = jnp.asarray(x[:split], jnp.float32)
    y_t = jnp.asarray(y[:split], jnp.int32)
    x_v = jnp.asarray(x[split:], jnp.float32)
    y_v = jnp.asarray(y[split:], jnp.int32)
    in_ch = x_t.shape[-1]
    n_train = x_t.shape[0]
    batch = min(batch, n_train)

    def child_init(key):
        return {
            "params": _supernet_init(
                key, num_layers, op_kernels, in_ch, channels, num_classes
            ),
            "step": jnp.asarray(0, jnp.int32),
        }

    def child_train_eval(child_state, arcs, key, active):
        del key
        params = child_state["params"]
        step = child_state["step"]
        weights = active.astype(jnp.float32)
        weights = weights / jnp.maximum(weights.sum(), 1.0)

        def one_train_step(i, st):
            params, step = st
            start = ((step + i) * batch) % jnp.maximum(n_train - batch + 1, 1)
            bx = jax.lax.dynamic_slice_in_dim(x_t, start, batch, axis=0)
            by = jax.lax.dynamic_slice_in_dim(y_t, start, batch, axis=0)

            def loss_fn(p):
                logits = jax.vmap(
                    lambda a: _supernet_apply(p, bx, a, num_layers, kinds)
                )(arcs)                                    # [K, B, classes]
                per_arc = optax.softmax_cross_entropy_with_integer_labels(
                    logits, by[None, :].repeat(arcs.shape[0], axis=0)
                ).mean(axis=1)                             # [K]
                return (per_arc * weights).sum()

            grads = jax.grad(loss_fn)(params)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads
            )
            return params, step

        params, _ = jax.lax.fori_loop(
            0, train_steps, one_train_step, (params, step)
        )

        def arc_acc(a):
            logits = _supernet_apply(params, x_v, a, num_layers, kinds)
            return (jnp.argmax(logits, -1) == y_v).mean()

        accs = jax.vmap(arc_acc)(arcs)
        return (
            {"params": params, "step": step + train_steps},
            accs.astype(jnp.float32),
        )

    goal = 1.0
    if spec.objective.type.value == "minimize":
        goal = -1.0
    return pop.enas_program(
        name="katib_tpu.models.enas_child:run_enas_trial",
        metric=spec.objective.objective_metric_name or "Validation-accuracy",
        n_population=k_pop,
        num_layers=num_layers,
        num_ops=len(ops),
        child_init=child_init,
        child_train_eval=child_train_eval,
        hidden_size=int(settings["controller_hidden_size"]),
        temperature=settings["controller_temperature"],
        tanh_const=settings["controller_tanh_const"],
        entropy_weight=settings["controller_entropy_weight"],
        baseline_decay=float(settings["controller_baseline_decay"]),
        learning_rate=float(settings["controller_learning_rate"]),
        skip_target=float(settings["controller_skip_target"]),
        skip_weight=settings["controller_skip_weight"],
        controller_steps=int(raw.get("fused_controller_steps", "10")),
        goal_scale=goal,
        seed=int(raw.get("random_state", "0") or 0),
    )


run_enas_trial.population_program = enas_population_program
