"""Decoder-only transformer LM with dp/fsdp/tp/sp sharding — the distributed
flagship workload of the trial runtime.

The reference framework contains no model code (distributed training is
delegated to PyTorchJob/MPIJob trials — SURVEY.md §2.9); this module is the
TPU-native equivalent deliverable: a trial workload that scales over a named
mesh with XLA collectives instead of NCCL/Horovod.

Sharding design (scaling-book recipe — pick a mesh, annotate, let XLA insert
collectives):
- activations: [B, T, E] with B over ('data','fsdp'), T over 'seq';
- attention: heads over 'model' (TP); sequence blocks over 'seq' via ring
  attention (katib_tpu.ops.ring_attention) — long-context first-class;
- params: column-parallel in-projections P(fsdp, model), row-parallel
  out-projections P(model, fsdp) — gradient reduce-scatters ride ICI;
- rotary embeddings are computed from *global* positions so sequence sharding
  is exact.

bfloat16 activations/matmuls with f32 params + optimizer state.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.flash_attention import flash_attention, sharded_flash_attention
from ..ops.ring_attention import dense_attention, ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    embed_dim: int = 512
    num_layers: int = 4
    num_heads: int = 8
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    causal: bool = True

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def rotary_embed(x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """RoPE on [B, T, H, D] with explicit global positions [B, T]."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32) * (math.log(10000.0) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


class Attention(nn.Module):
    config: TransformerConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        h, d = cfg.num_heads, cfg.head_dim
        qkv = nn.DenseGeneral((3, h, d), use_bias=False, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = rotary_embed(q, positions)
        k = rotary_embed(k, positions)
        if self.mesh is not None:
            from ..parallel.mesh import mesh_axis_sizes

            sizes = mesh_axis_sizes(self.mesh)
            if sizes.get("seq", 1) > 1:
                # cross-device sequence blocks: ring schedule over ppermute
                o = ring_attention(q, k, v, self.mesh, causal=cfg.causal)
            else:
                # seq unsharded: fused Pallas flash kernel per local shard
                o = sharded_flash_attention(q, k, v, self.mesh, causal=cfg.causal)
        else:
            o = flash_attention(q, k, v, causal=cfg.causal)
        return nn.DenseGeneral(
            cfg.embed_dim, axis=(-2, -1), use_bias=False, dtype=cfg.dtype, name="out"
        )(o)


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        hidden = cfg.embed_dim * cfg.mlp_ratio
        up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="up")(x)
        gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="gate")(x)
        return nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype, name="down")(
            nn.silu(gate) * up
        )


class Block(nn.Module):
    config: TransformerConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, positions):
        x = x + Attention(self.config, self.mesh, name="attn")(
            RMSNorm(name="ln1")(x), positions
        )
        x = x + MLP(self.config, name="mlp")(RMSNorm(name="ln2")(x))
        return x


class TransformerLM(nn.Module):
    config: TransformerConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, tokens, positions=None):
        cfg = self.config
        if positions is None:
            b, t = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
        emb = self.param(
            "embed", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.embed_dim), jnp.float32
        )
        x = emb[tokens].astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = Block(cfg, self.mesh, name=f"block{i}")(x, positions)
        x = RMSNorm(name="ln_f")(x)
        # tied output head
        logits = jnp.einsum("bte,ve->btv", x.astype(jnp.float32), emb)
        return logits


# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------

def param_sharding_rules(path: Tuple[str, ...]):
    """Param-tree path -> PartitionSpec (TP column/row split + fsdp)."""
    from jax.sharding import PartitionSpec as P

    name = "/".join(path)
    if "qkv/kernel" in name:
        return P("fsdp", None, "model", None)     # [E, 3, H, D]
    if "attn/out/kernel" in name:
        return P("model", None, "fsdp")           # [H, D, E]
    if "up/kernel" in name or "gate/kernel" in name:
        return P("fsdp", "model")                 # [E, F]
    if "down/kernel" in name:
        return P("model", "fsdp")                 # [F, E]
    if name == "embed":
        return P(None, "fsdp")                    # [V, E]
    return P()  # replicated (norms, biases)


def shard_params(params: Dict[str, Any], mesh) -> Dict[str, Any]:
    """Apply rules with jax.device_put (NamedSharding)."""
    import flax
    from jax.sharding import NamedSharding

    flat = flax.traverse_util.flatten_dict(params)
    out = {
        k: jax.device_put(v, NamedSharding(mesh, param_sharding_rules(k)))
        for k, v in flat.items()
    }
    return flax.traverse_util.unflatten_dict(out)


def param_spec_tree(params: Dict[str, Any]):
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    specs = {k: param_sharding_rules(k) for k in flat}
    return flax.traverse_util.unflatten_dict(specs)
