"""Decoder-only transformer LM with dp/fsdp/tp/sp sharding — the distributed
flagship workload of the trial runtime.

The reference framework contains no model code (distributed training is
delegated to PyTorchJob/MPIJob trials — SURVEY.md §2.9); this module is the
TPU-native equivalent deliverable: a trial workload that scales over a named
mesh with XLA collectives instead of NCCL/Horovod.

Sharding design (scaling-book recipe — pick a mesh, annotate, let XLA insert
collectives):
- activations: [B, T, E] with B over ('data','fsdp'), T over 'seq';
- attention: heads over 'model' (TP); sequence blocks over 'seq' via ring
  attention (katib_tpu.ops.ring_attention) — long-context first-class;
- params: column-parallel in-projections P(fsdp, model), row-parallel
  out-projections P(model, fsdp) — gradient reduce-scatters ride ICI;
- rotary embeddings are computed from *global* positions so sequence sharding
  is exact.

bfloat16 activations/matmuls with f32 params + optimizer state.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.flash_attention import flash_attention, sharded_flash_attention
from ..ops.ring_attention import dense_attention, ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    embed_dim: int = 512
    num_layers: int = 4
    num_heads: int = 8
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    causal: bool = True
    # Mixture-of-experts: 0 = dense MLP in every block; otherwise every block
    # uses a top-1 routed MoE with experts sharded over the mesh 'expert' axis.
    num_experts: int = 0
    expert_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def bench_lm_config(size: str, on_tpu: bool):
    """The canonical benchmark LM shapes — single source for bench.py,
    scripts/tune_tpu.py and scripts/profile_lm.py so a retune can't leave
    one of them measuring a stale configuration. Returns
    ``(config_kwargs, batch, seq, effective_size)``; off-TPU every size
    degrades to the sub-minute CPU smoke shape (and says so in
    ``effective_size``)."""
    if not on_tpu:
        return (
            dict(vocab_size=512, embed_dim=128, num_layers=2, num_heads=4,
                 max_seq_len=256, dtype=jnp.float32),
            4, 256, "cpu_smoke",
        )
    if size == "large":
        return (
            dict(vocab_size=32768, embed_dim=1024, num_layers=8, num_heads=16,
                 max_seq_len=2048, dtype=jnp.bfloat16),
            4, 2048, "large",
        )
    return (
        dict(vocab_size=8192, embed_dim=512, num_layers=4, num_heads=8,
             max_seq_len=1024, dtype=jnp.bfloat16),
        8, 1024, "small",
    )


def rotary_embed(x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """RoPE on [B, T, H, D] with explicit global positions [B, T]."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32) * (math.log(10000.0) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


class Attention(nn.Module):
    config: TransformerConfig
    mesh: Optional[Any] = None
    # Set when this module is traced INSIDE a shard_map that is manual over
    # a sequence axis (pipeline stages with sequence parallelism): attention
    # runs the ring schedule directly over that axis instead of wrapping its
    # own shard_map. positions must be GLOBAL (caller offsets by rank).
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        h, d = cfg.num_heads, cfg.head_dim
        qkv = nn.DenseGeneral((3, h, d), use_bias=False, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = rotary_embed(q, positions)
        k = rotary_embed(k, positions)
        if self.seq_axis is not None:
            from ..ops.ring_attention import ring_attention_local

            o = ring_attention_local(q, k, v, self.seq_axis, causal=cfg.causal)
        elif self.mesh is not None:
            from ..parallel.mesh import mesh_axis_sizes

            sizes = mesh_axis_sizes(self.mesh)
            # 'expert' is a batch axis here: outside the MoE layers it acts
            # as pure data parallelism (see parallel.mesh.activation_batch_axes)
            batch_axes = ("data", "fsdp", "expert")
            if sizes.get("seq", 1) > 1:
                # cross-device sequence blocks: ring schedule over ppermute
                o = ring_attention(
                    q, k, v, self.mesh, causal=cfg.causal, batch_axes=batch_axes
                )
            else:
                # seq unsharded: fused Pallas flash kernel per local shard
                o = sharded_flash_attention(
                    q, k, v, self.mesh, causal=cfg.causal, batch_axes=batch_axes
                )
        else:
            o = flash_attention(q, k, v, causal=cfg.causal)
        return nn.DenseGeneral(
            cfg.embed_dim, axis=(-2, -1), use_bias=False, dtype=cfg.dtype, name="out"
        )(o)


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        hidden = cfg.embed_dim * cfg.mlp_ratio
        up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="up")(x)
        gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="gate")(x)
        return nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype, name="down")(
            nn.silu(gate) * up
        )


class MoE(nn.Module):
    """Top-1 routed mixture-of-experts FFN (Switch style) with experts laid
    out over the mesh 'expert' axis.

    Expert parallelism, TPU-native: per-expert FFN weights are [X, E, F]
    sharded P('expert', 'fsdp', 'model'); the dispatched token buffer
    [B, X, C, E] carries a sharding constraint that puts X on 'expert', so XLA
    inserts the token all-to-all over ICI (the reference delegates any such
    layout to trial-image NCCL — SURVEY.md §2.9). A load-balance aux loss is
    sown under 'intermediates'/'moe_aux_loss' for the train step to collect.
    """

    config: TransformerConfig
    mesh: Optional[Any] = None
    # Set when traced INSIDE a shard_map already manual over an expert axis
    # (pipeline stages with expert parallelism): the module's FFN weights
    # are created at their LOCAL shard shape [X/ep, E, F] and the token
    # exchange is a direct all_to_all over the axis — no nested shard_map.
    expert_axis: Optional[str] = None
    expert_axis_size: int = 1

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, t, e = x.shape
        nx = cfg.num_experts
        hidden = cfg.embed_dim * cfg.mlp_ratio
        capacity = max(1, int(cfg.expert_capacity_factor * t / nx))

        router_logits = nn.Dense(nx, use_bias=False, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)
        )  # [B, T, X]
        probs = jax.nn.softmax(router_logits, axis=-1)
        gate = jnp.max(probs, axis=-1)          # [B, T]
        expert_idx = jnp.argmax(probs, axis=-1)  # [B, T]

        onehot = jax.nn.one_hot(expert_idx, nx, dtype=jnp.float32)  # [B, T, X]
        # position of each token within its expert's buffer, per batch row
        pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0             # [B, T, X]
        keep = (pos >= 0) & (pos < capacity)
        dispatch = onehot[..., None] * jax.nn.one_hot(
            jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity, dtype=jnp.float32
        )  # [B, T, X, C]
        dispatch = jnp.where(keep[..., None], dispatch, 0.0)
        combine = dispatch * gate[:, :, None, None]

        # load balance: fraction of tokens per expert vs mean router prob
        frac_tokens = jnp.mean(onehot, axis=(0, 1))
        frac_probs = jnp.mean(probs, axis=(0, 1))
        aux = cfg.moe_aux_weight * nx * jnp.sum(frac_tokens * frac_probs)
        self.sow("intermediates", "moe_aux_loss", aux)

        # in the manual (in-pipeline) mode the FFN weights live at their
        # LOCAL shard shape — the stage's shard_map in_specs put 'expert'
        # on the X dim, so each device holds nx/ep experts
        nx_local = nx
        if self.expert_axis is not None:
            assert nx % self.expert_axis_size == 0, (
                f"num_experts {nx} not divisible by expert axis "
                f"{self.expert_axis_size}"
            )
            nx_local = nx // self.expert_axis_size
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (nx_local, e, hidden), jnp.float32
        )
        w_gate = self.param(
            "w_gate", nn.initializers.lecun_normal(), (nx_local, e, hidden), jnp.float32
        )
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (nx_local, hidden, e), jnp.float32
        )
        if self.mesh is not None:
            # ZeRO idiom (as for the embed table): expert weights are STORED
            # with 'fsdp' on the embed dim but COMPUTED gathered — otherwise
            # the FFN einsums propagate embed-dim-over-'fsdp' onto the
            # activations, which can't meet the batch-sharded residual layout
            # without an involuntary full rematerialization. 'expert' and
            # 'model' stay sharded at compute time.
            from jax.sharding import NamedSharding, PartitionSpec as P

            w_in = jax.lax.with_sharding_constraint(
                w_in, NamedSharding(self.mesh, P("expert", None, "model"))
            )
            w_gate = jax.lax.with_sharding_constraint(
                w_gate, NamedSharding(self.mesh, P("expert", None, "model"))
            )
            w_out = jax.lax.with_sharding_constraint(
                w_out, NamedSharding(self.mesh, P("expert", "model", None))
            )

        ep = bp = 1
        if self.mesh is not None:
            from ..parallel.mesh import mesh_axis_sizes

            sizes = mesh_axis_sizes(self.mesh)
            ep = sizes.get("expert", 1)
            bp = ep * sizes.get("data", 1) * sizes.get("fsdp", 1)

        def _ffn(expert_in, w_in, w_gate, w_out):
            h = jnp.einsum("bxce,xef->bxcf", expert_in, w_in.astype(cfg.dtype))
            g = jnp.einsum("bxce,xef->bxcf", expert_in, w_gate.astype(cfg.dtype))
            return jnp.einsum(
                "bxcf,xfe->bxce", nn.silu(g) * h, w_out.astype(cfg.dtype)
            )

        def _a2a_dispatch_ffn_combine(dispatch, combine, x, w_in, w_gate, w_out, axis):
            expert_in = jnp.einsum(
                "btxc,bte->bxce", dispatch.astype(cfg.dtype), x
            )  # [b_local, X, C, E]
            expert_in = jax.lax.all_to_all(
                expert_in, axis, split_axis=1, concat_axis=0, tiled=True
            )  # [b_local·ep, X/ep, C, E] — each device holds ITS experts' tokens
            out = _ffn(expert_in, w_in, w_gate, w_out)
            out = jax.lax.all_to_all(
                out, axis, split_axis=0, concat_axis=1, tiled=True
            )  # [b_local, X, C, E] — tokens return to their batch shard
            return jnp.einsum("btxc,bxce->bte", combine.astype(cfg.dtype), out)

        if self.expert_axis is not None and self.expert_axis_size > 1:
            # already inside a manual shard_map (pipeline stage): exchange
            # tokens directly over the axis, weights are pre-sharded
            return _a2a_dispatch_ffn_combine(
                dispatch, combine, x, w_in, w_gate, w_out, self.expert_axis
            )

        if ep > 1 and nx % ep == 0 and b % bp == 0:
            # Explicit expert parallelism: tokens arrive batch-sharded over
            # data×fsdp×expert (activation_batch_axes), each device builds
            # its batch shard's dispatch buffer locally, and ONE tiled
            # all_to_all per direction exchanges batch-shards for
            # expert-shards over the ICI 'expert' axis — where GSPMD's
            # fallback lowering (all-gather + slice) moves ep× the bytes and
            # replicates the FFN compute. The batch axes are manual so the
            # body stays batch-sharded end to end; only 'model' (TP on the
            # expert FFN matmuls) remains a GSPMD-auto axis.
            from jax.sharding import PartitionSpec as P

            def dispatch_ffn_combine(dispatch, combine, x, w_in, w_gate, w_out):
                return _a2a_dispatch_ffn_combine(
                    dispatch, combine, x, w_in, w_gate, w_out, "expert"
                )

            batch_axes = ("data", "fsdp", "expert")
            ein_spec = P(batch_axes, None, None, None)
            w_spec = P("expert", None, None)  # replicated over data/fsdp,
            fn = jax.shard_map(                   # 'model' TP stays auto
                dispatch_ffn_combine,
                mesh=self.mesh,
                in_specs=(ein_spec, ein_spec, P(batch_axes, None, None),
                          w_spec, w_spec, w_spec),
                out_specs=P(batch_axes, None, None),
                check_vma=False,
                axis_names={"data", "fsdp", "expert"},
            )
            # jit wrapper: a partial-manual shard_map (axis_names ⊂ mesh
            # axes) only traces under jit; the wrapper inlines when the
            # caller is already jitted and makes eager apply/init work too.
            # Always reached under the caller's jit trace in the train path,
            # so the fresh wrapper is traced once per outer compile — not a
            # per-step recompile; only repeated EAGER calls would re-trace.
            return jax.jit(fn)(dispatch, combine, x, w_in, w_gate, w_out)  # katib-check: ignore[KTC105] inlined under the caller's jit

        expert_in = jnp.einsum(
            "btxc,bte->bxce", dispatch.astype(cfg.dtype), x
        )  # [B, X, C, E]
        out = _ffn(expert_in, w_in, w_gate, w_out)
        return jnp.einsum("btxc,bxce->bte", combine.astype(cfg.dtype), out)


def collect_moe_aux(mutated) -> jnp.ndarray:
    """Sum every sown 'moe_aux_loss' leaf from a ``mutable=['intermediates']``
    apply result — the one place the sow key is interpreted (used by both
    the jit train step and the pipeline's stage loop)."""
    import flax

    flat = flax.traverse_util.flatten_dict(mutated.get("intermediates", {}))
    return jnp.float32(
        sum(jnp.sum(jnp.asarray(v)) for k, v in flat.items() if "moe_aux_loss" in k)
    )


def _pin_residual(x, mesh):
    """Pin the residual stream [B, T, E] to its canonical layout (batch over
    'data'/'fsdp', sequence over 'seq', embed replicated).

    Without this, GSPMD propagates layouts *through* the residual adds — e.g.
    the MoE dispatch's batch-over-'expert' sharding meets ring attention's
    seq-sharded shard_map boundary and the partitioner falls back to an
    involuntary full rematerialization (replicate, then re-partition) of the
    activation every step. An explicit constraint at each block boundary
    keeps every transition a cheap all-to-all/collective-permute."""
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import activation_batch_axes, mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    b, t, _ = x.shape
    batch_axes = activation_batch_axes(sizes, b) or None
    seq_axis = "seq" if sizes.get("seq", 1) > 1 and t % sizes["seq"] == 0 else None
    if batch_axes is None and seq_axis is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(batch_axes, seq_axis, None))
    )


class Block(nn.Module):
    config: TransformerConfig
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None      # see Attention.seq_axis
    expert_axis: Optional[str] = None   # see MoE.expert_axis
    expert_axis_size: int = 1

    @nn.compact
    def __call__(self, x, positions):
        x = _pin_residual(
            x + Attention(self.config, self.mesh, self.seq_axis, name="attn")(
                RMSNorm(name="ln1")(x), positions
            ),
            self.mesh,
        )
        if self.config.num_experts > 0:
            x = x + MoE(
                self.config, self.mesh, self.expert_axis, self.expert_axis_size,
                name="moe",
            )(RMSNorm(name="ln2")(x))
        else:
            x = x + MLP(self.config, name="mlp")(RMSNorm(name="ln2")(x))
        return _pin_residual(x, self.mesh)


class TransformerLM(nn.Module):
    config: TransformerConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, tokens, positions=None):
        cfg = self.config
        if positions is None:
            b, t = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
        emb = self.param(
            "embed", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.embed_dim), jnp.float32
        )
        if self.mesh is not None:
            # ZeRO idiom: the table is STORED sharded over 'fsdp'
            # (param_sharding_rules) but COMPUTED replicated — one cheap
            # [V, E] all-gather here instead of the involuntary full
            # rematerialization the partitioner otherwise emits for the
            # token-gather forward (and its scatter-add transpose), whose
            # activations can't transition from embed-dim-sharded to
            # batch-sharded efficiently.
            from jax.sharding import NamedSharding, PartitionSpec as P

            emb = jax.lax.with_sharding_constraint(
                emb, NamedSharding(self.mesh, P(None, None))
            )
        x = _pin_residual(emb[tokens].astype(cfg.dtype), self.mesh)
        for i in range(cfg.num_layers):
            x = Block(cfg, self.mesh, name=f"block{i}")(x, positions)
        x = RMSNorm(name="ln_f")(x)
        # tied output head — the largest matmul in the model: bf16 operands
        # at native MXU rate, f32 accumulation for the softmax/loss
        logits = jnp.einsum(
            "bte,ve->btv", x.astype(cfg.dtype), emb.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        return logits


def abstract_lm_program(assignments: Dict[str, str]):
    """Abstract program probe (katib_tpu.analysis.program) for the LM trial
    (parallel/train.py:run_lm_trial): the canonical jitted train step traced
    from ShapeDtypeStruct avals — eval_shape init, no mesh, no devices.

    learning_rate enters as a traced f32 scalar (runtime-scalar); every
    architecture/shape knob (embed_dim, num_layers, num_heads, batch_size,
    seq_len, vocab_size) changes avals, and the parallelism degrees select
    a different sharded program, so all of those are fingerprint material
    (shape-affecting); num_steps/profile are host-side knobs."""
    from ..analysis.program import ProgramProbe

    config = TransformerConfig(
        vocab_size=int(assignments.get("vocab_size", "512")),
        embed_dim=int(assignments.get("embed_dim", "128")),
        num_layers=int(assignments.get("num_layers", "2")),
        num_heads=int(assignments.get("num_heads", "4")),
        max_seq_len=int(assignments.get("seq_len", "128")),
    )
    batch = int(assignments.get("batch_size", "8"))
    seq = int(assignments.get("seq_len", "128"))
    model = TransformerLM(config)  # mesh-free abstract twin; the mesh
    # layout enters the fingerprint through `statics` below instead
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    targets = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    params = jax.eval_shape(
        lambda r, t: model.init(r, t)["params"], rng, tokens
    )

    def train_step(params, lr, tokens, targets):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return nll.mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return ProgramProbe(
        fn=train_step,
        args=(params, lr, tokens, targets),
        params=params,
        hyperparams={"learning_rate": lr},
        host_params={"num_steps", "profile"},
        statics={
            "tensor_parallel": int(assignments.get("tensor_parallel", "1")),
            "sequence_parallel": int(assignments.get("sequence_parallel", "1")),
        },
    )


# ---------------------------------------------------------------------------
# Sharding rules
# ---------------------------------------------------------------------------

def param_sharding_rules(path: Tuple[str, ...]):
    """Param-tree path -> PartitionSpec (TP column/row split + fsdp)."""
    from jax.sharding import PartitionSpec as P

    name = "/".join(path)
    if "qkv/kernel" in name:
        return P("fsdp", None, "model", None)     # [E, 3, H, D]
    if "attn/out/kernel" in name:
        return P("model", None, "fsdp")           # [H, D, E]
    if "up/kernel" in name or "gate/kernel" in name:
        return P("fsdp", "model")                 # [E, F]
    if "down/kernel" in name:
        return P("model", "fsdp")                 # [F, E]
    if "moe/w_in" in name or "moe/w_gate" in name:
        return P("expert", "fsdp", "model")       # [X, E, F]
    if "moe/w_out" in name:
        return P("expert", "model", "fsdp")       # [X, F, E]
    if name == "embed":
        return P(None, "fsdp")                    # [V, E]
    return P()  # replicated (norms, biases, router)


def shard_params(params: Dict[str, Any], mesh) -> Dict[str, Any]:
    """Apply rules with jax.device_put (NamedSharding)."""
    import flax
    from jax.sharding import NamedSharding

    flat = flax.traverse_util.flatten_dict(params)
    out = {
        k: jax.device_put(v, NamedSharding(mesh, param_sharding_rules(k)))
        for k, v in flat.items()
    }
    return flax.traverse_util.unflatten_dict(out)


def param_spec_tree(params: Dict[str, Any]):
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    specs = {k: param_sharding_rules(k) for k in flat}
    return flax.traverse_util.unflatten_dict(specs)
