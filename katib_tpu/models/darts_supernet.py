"""DARTS supernet (search network) in flax.

reference examples/v1beta1/trial-images/darts-cnn-cifar10/model.py
(Cell, NetworkCNN) + search_space.py (genotype parsing). Structure matched:

- stem: 3x3 conv to stem_multiplier*init_channels;
- num_layers cells; reduction cells (stride 2, doubled channels) at layers
  [L/3, 2L/3] (L==2: second layer; L==1: none);
- each cell: 2 preprocessed inputs (FactorizedReduce after a reduction cell),
  num_nodes intermediate nodes, node i has 2+i mixed-op edges; cell output is
  the concat of intermediate node states;
- two alpha sets (normal/reduce), one [i+2, n_ops] matrix per node,
  initialized 1e-3*randn; softmaxed per-edge before the forward pass;
- genotype: per node keep top-2 edges by max non-'none' op weight
  (search_space.py parse).

TPU-first: pure function of (weights, alphas, x) — alphas live in a separate
param collection ("alphas") so bilevel optimization can take grads per group;
NHWC; all cells unrolled at trace time (static num_layers).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.darts_ops import FactorizedReduce, MatmulConv, MixedOp, StdConv, batch_norm


class Cell(nn.Module):
    """model.py Cell."""

    primitives: Sequence[str]
    num_nodes: int
    channels: int
    reduction_prev: bool
    reduction_cur: bool

    @nn.compact
    def __call__(self, s0, s1, w_dag):
        if self.reduction_prev:
            s0 = FactorizedReduce(channels=self.channels, name="pre0_reduce")(s0)
        else:
            s0 = StdConv(channels=self.channels, kernel_size=1, name="pre0")(s0)
        s1 = StdConv(channels=self.channels, kernel_size=1, name="pre1")(s1)

        states = [s0, s1]
        for i in range(self.num_nodes):
            acc = None
            for j in range(2 + i):
                stride = 2 if self.reduction_cur and j < 2 else 1
                out = MixedOp(
                    primitives=self.primitives,
                    channels=self.channels,
                    stride=stride,
                    name=f"node{i}_edge{j}",
                )(states[j], w_dag[i][j])
                acc = out if acc is None else acc + out
            states.append(acc)
        return jnp.concatenate(states[2:], axis=-1)


class DartsSupernet(nn.Module):
    """model.py NetworkCNN."""

    primitives: Sequence[str]      # includes 'none' (appended by SearchSpace)
    init_channels: int = 16
    input_channels: int = 3
    num_classes: int = 10
    num_layers: int = 8
    num_nodes: int = 4
    stem_multiplier: int = 3
    # Rematerialize each cell in the backward pass (jax.checkpoint via
    # nn.remat): the supernet's activation memory is dominated by the |O|
    # parallel mixed-op outputs per edge per cell, and the second-order
    # architect differentiates through five forward/backward passes — remat
    # caps stored activations at cell boundaries (O(num_layers) tensors)
    # at the cost of one extra forward per cell in the backward. This is
    # the TPU answer to SURVEY §7 hard part 1's "memory of the supernet":
    # trade MXU FLOPs (abundant) for HBM (the bottleneck).
    remat_cells: bool = False

    def reduction_layers(self) -> List[int]:
        if self.num_layers == 1:
            return []
        if self.num_layers == 2:
            return [1]
        return [self.num_layers // 3, 2 * self.num_layers // 3]

    @nn.compact
    def __call__(self, x):
        n_ops = len(self.primitives)
        # alphas in their own collection for bilevel grad separation
        alpha_normal = [
            self.param(
                f"alpha_normal_{i}",
                lambda key, shape: 1e-3 * jax.random.normal(key, shape),
                (i + 2, n_ops),
            )
            for i in range(self.num_nodes)
        ]
        alpha_reduce = (
            [
                self.param(
                    f"alpha_reduce_{i}",
                    lambda key, shape: 1e-3 * jax.random.normal(key, shape),
                    (i + 2, n_ops),
                )
                for i in range(self.num_nodes)
            ]
            if self.num_layers > 1
            else []
        )

        w_normal = [jax.nn.softmax(a, axis=-1) for a in alpha_normal]
        w_reduce = [jax.nn.softmax(a, axis=-1) for a in alpha_reduce]

        c_cur = self.stem_multiplier * self.init_channels
        s = MatmulConv(c_cur, (3, 3), name="stem")(x)
        s = batch_norm(s)
        s0 = s1 = s

        reductions = self.reduction_layers()
        cell_cls = nn.remat(Cell) if self.remat_cells else Cell
        c = self.init_channels
        reduction_prev = False
        for layer in range(self.num_layers):
            reduction_cur = layer in reductions
            if reduction_cur:
                c *= 2
            cell = cell_cls(
                primitives=self.primitives,
                num_nodes=self.num_nodes,
                channels=c,
                reduction_prev=reduction_prev,
                reduction_cur=reduction_cur,
                name=f"cell{layer}",
            )
            w_dag = w_reduce if reduction_cur else w_normal
            s0, s1 = s1, cell(s0, s1, w_dag)
            reduction_prev = reduction_cur

        out = s1.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, name="classifier")(out)


def split_params(params: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Split a flax param tree into (weights, alphas) masks for two-group
    optimization (model.py getWeights/getAlphas)."""
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    weights = {k: v for k, v in flat.items() if not k[-1].startswith("alpha_")}
    alphas = {k: v for k, v in flat.items() if k[-1].startswith("alpha_")}
    return (
        flax.traverse_util.unflatten_dict(weights),
        flax.traverse_util.unflatten_dict(alphas),
    )


def merge_params(weights: Dict[str, Any], alphas: Dict[str, Any]) -> Dict[str, Any]:
    import flax

    flat = dict(flax.traverse_util.flatten_dict(weights))
    flat.update(flax.traverse_util.flatten_dict(alphas))
    return flax.traverse_util.unflatten_dict(flat)


def parse_genotype(
    alphas: Sequence[jnp.ndarray], primitives: Sequence[str], k: int = 2
) -> List[List[Tuple[str, int]]]:
    """search_space.py parse: discretize one alpha set into a gene.

    For each node: per-edge best non-'none' op, then keep the top-k edges by
    that op's weight. 'none' must be the last primitive.
    """
    assert primitives[-1] == "none"
    gene: List[List[Tuple[str, int]]] = []
    for edges in alphas:
        w = jax.nn.softmax(jnp.asarray(edges), axis=-1)[:, :-1]  # drop 'none'
        best_op = jnp.argmax(w, axis=-1)               # [n_edges]
        best_w = jnp.max(w, axis=-1)                   # [n_edges]
        top_edges = jnp.argsort(-best_w)[:k]
        gene.append(
            [(primitives[int(best_op[e])], int(e)) for e in sorted(map(int, top_edges))]
        )
    return gene


def genotype(params: Dict[str, Any], primitives: Sequence[str], num_nodes: int) -> Dict[str, Any]:
    """model.py genotype(): normal + reduce genes with concat range."""
    _, alphas = split_params(params)
    import flax

    flat = flax.traverse_util.flatten_dict(alphas)

    def node_index(key) -> int:  # numeric sort: alpha_normal_10 after _9
        return int(key[-1].rsplit("_", 1)[1])

    keys = sorted(flat, key=node_index)
    normal = [flat[k] for k in keys if k[-1].startswith("alpha_normal_")]
    reduce_ = [flat[k] for k in keys if k[-1].startswith("alpha_reduce_")]
    gene = {
        "normal": parse_genotype(normal, primitives),
        "normal_concat": list(range(2, 2 + num_nodes)),
    }
    if reduce_:
        gene["reduce"] = parse_genotype(reduce_, primitives)
        gene["reduce_concat"] = list(range(2, 2 + num_nodes))
    return gene
