"""DARTS bilevel search trainer — the TPU re-design of the reference's
darts-cnn-cifar10 trial image.

reference examples/v1beta1/trial-images/darts-cnn-cifar10/run_trial.py:29-259
(alternating alpha/weight optimization, SGD+cosine for weights, Adam for
alphas, grad clip, prints Best-Genotype) and architect.py:19-135 (second-order
unrolled alpha gradient).

JAX re-design:
- the whole search step — virtual SGD step w', validation grads at w',
  exact jvp Hessian-vector correction (hessian_mode="jvp"; the reference's
  central difference remains as "fd"), alpha Adam update, then the real
  weight update — is ONE jitted pure function; XLA fuses the
  forward/backward passes and keeps everything resident in HBM;
- second-order terms are plain jax.grad compositions (no parameter copying:
  the virtual model is just a tree_map expression);
- data parallelism: the step is jitted with NamedSharding over a 1-D device
  mesh ('data'); batch-sharded inputs make XLA insert psum for the gradient
  all-reduce over ICI (multi-chip DARTS, SURVEY.md §7 hard part 1);
- bfloat16 matmuls via jax.default_matmul_precision can be toggled by the
  caller; parameters stay f32;
- optimizer hyperparameters (w_lr, alpha_lr, momentum, weight decays) are
  TRACED arguments of the jitted step, not baked-in constants: every trial of
  an HPO sweep over them reuses ONE compiled XLA program — no per-trial
  recompile (the reference pays a fresh CUDA-graph warmup per trial process).

Entry point ``run_darts_trial(assignments, ctx)`` consumes the suggestion's
``algorithm-settings`` / ``search-space`` / ``num-layers`` JSON assignments
exactly like run_trial.py parses its flags.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..utils.datasets import batches, load_cifar10
from .darts_supernet import DartsSupernet, genotype, merge_params, split_params


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def _loss_fn(model: DartsSupernet, weights, alphas, batch) -> jnp.ndarray:
    x, y = batch
    logits = model.apply({"params": merge_params(weights, alphas)}, x)
    return cross_entropy(logits, y)


def _tree_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.vdot(l, l) for l in leaves))


def architect_alpha_grad(
    model: DartsSupernet,
    weights,
    alphas,
    momentum_buf,
    train_batch,
    valid_batch,
    xi: float,
    w_momentum: float,
    w_weight_decay: float,
    hessian_mode: str = "jvp",
):
    """Unrolled second-order alpha gradient (architect.py:30-135).

    dalpha L_val(w', a) - xi * d^2/dadw L_train(w, a) . dw' L_val(w', a)

    ``hessian_mode`` selects how the mixed Hessian-vector product is
    computed:

    - ``"jvp"`` (default): EXACT forward-over-reverse ``jax.jvp`` through
      the alpha-gradient map — the idiomatic JAX form, one extra
      forward-mode pass instead of two extra backward passes.
    - ``"fd"``: the reference's central-difference approximation
      (architect.py compute_hessian, eps = 0.01/||dw||), kept for parity
      comparison. Measured against the exact product (f64): because
      dalpha L_train is DISCONTINUOUS in w at every ReLU/pooling
      activation boundary, the finite difference is O(jump/eps) garbage
      whenever the +/-eps probe straddles a boundary — 8-90x relative
      error on a small supernet — while converging to the jvp value when
      eps happens to be smaller than the nearest kink distance. The
      reference tolerates this because xi is small and the noise averages
      out over many alternating steps; the exact product removes it for
      free (torch-era double-backward constraints don't apply to XLA).
    """
    # virtual step: w' = w - xi * (momentum*buf + dw L_train + wd*w)
    g_w = jax.grad(lambda w: _loss_fn(model, w, alphas, train_batch))(weights)
    v_weights = jax.tree.map(
        lambda w, g, m: w - xi * (w_momentum * m + g + w_weight_decay * w),
        weights,
        g_w,
        momentum_buf,
    )

    # validation grads at (w', alpha) — one joint backward pass for both
    # cotangents (graph size == compile time on TPU; see bench.py)
    val_loss = lambda w, a: _loss_fn(model, w, a, valid_batch)
    dw, dalpha = jax.grad(val_loss, argnums=(0, 1))(v_weights, alphas)

    train_alpha_grad = lambda w: jax.grad(
        lambda a: _loss_fn(model, w, a, train_batch)
    )(alphas)
    if hessian_mode == "jvp":
        # exact d^2/dadw L_train . dw via forward-over-reverse
        _, hessian = jax.jvp(train_alpha_grad, (weights,), (dw,))
    elif hessian_mode == "fd":
        # reference central difference (compute_hessian): eps = 0.01 / ||dw||
        eps = 0.01 / (_tree_norm(dw) + 1e-12)
        w_pos = jax.tree.map(lambda w, d: w + eps * d, weights, dw)
        w_neg = jax.tree.map(lambda w, d: w - eps * d, weights, dw)
        a_pos = train_alpha_grad(w_pos)
        a_neg = train_alpha_grad(w_neg)
        hessian = jax.tree.map(lambda p, n: (p - n) / (2.0 * eps), a_pos, a_neg)
    else:
        raise ValueError(f"unknown hessian_mode {hessian_mode!r} (jvp|fd)")

    return jax.tree.map(lambda da, h: da - xi * h, dalpha, hessian)


def _make_w_tx(weight_decay, momentum, lr, grad_clip):
    """SGD momentum + weight decay + clip (run_trial.py w_optim). Pure
    construction — safe to rebuild inside the traced step with traced
    hyperparameter values (state structure is value-independent)."""
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.clip_by_global_norm(grad_clip),
        optax.sgd(lr, momentum=momentum),
    )


def _make_a_tx(weight_decay, lr):
    """Adam(0.5, 0.999) + weight decay (run_trial.py alpha_optim)."""
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.adam(lr, b1=0.5, b2=0.999),
    )


@functools.lru_cache(maxsize=16)
def _compiled_search_step(model: "DartsSupernet", total_steps: int,
                          w_lr_min: float, w_grad_clip: float,
                          hessian_mode: str = "jvp"):
    """ONE jitted bilevel step per static configuration, shared across
    DartsSearch instances (flax Modules are frozen dataclasses — hashable
    cache keys). Every trial of an HPO sweep reuses the same Python
    callable, so trials 2+ skip jax retracing entirely on top of the
    persistent-XLA-cache compile hit; hyperparameter VALUES arrive through
    the traced ``hyper`` argument.

    Memory bound: the cache pins up to maxsize compiled executables (plus
    their Module keys) for the life of the process — sized for HPO sweeps,
    which iterate one static config. A controller sweeping MANY distinct
    architectures holds ≤16 programs; lower maxsize (or clear the caches via
    ``_compiled_search_step.cache_clear()``) if device/host memory pressure
    shows up before eviction does."""

    def momentum_of(opt_state):
        # trace of optax.sgd momentum buffer inside the chain
        return opt_state[2][0].trace

    def step(weights, alphas, w_opt_state, a_opt_state, step_idx, hyper, train_batch, valid_batch):
        # cosine decay from the traced base lr (run_trial.py lr_scheduler):
        # lr(t) = w_lr_min + (w_lr - w_lr_min) * 0.5 * (1 + cos(pi t/T))
        frac = jnp.clip(step_idx / total_steps, 0.0, 1.0)
        xi = w_lr_min + (hyper["w_lr"] - w_lr_min) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        w_tx = _make_w_tx(hyper["w_weight_decay"], hyper["w_momentum"], xi, w_grad_clip)
        a_tx = _make_a_tx(hyper["alpha_weight_decay"], hyper["alpha_lr"])

        # 1) alpha update from the unrolled objective
        dalpha = architect_alpha_grad(
            model,
            weights,
            alphas,
            momentum_of(w_opt_state),
            train_batch,
            valid_batch,
            xi,
            hyper["w_momentum"],
            hyper["w_weight_decay"],
            hessian_mode=hessian_mode,
        )
        a_updates, a_opt_state = a_tx.update(dalpha, a_opt_state, alphas)
        alphas = optax.apply_updates(alphas, a_updates)

        # 2) weight update on the training batch
        loss, g_w = jax.value_and_grad(
            lambda w: _loss_fn(model, w, alphas, train_batch)
        )(weights)
        w_updates, w_opt_state = w_tx.update(g_w, w_opt_state, weights)
        weights = optax.apply_updates(weights, w_updates)
        return weights, alphas, w_opt_state, a_opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1, 2, 3))


@functools.lru_cache(maxsize=16)
def _compiled_eval_step(model: "DartsSupernet"):
    def evaluate(weights, alphas, batch):
        x, y = batch
        logits = model.apply({"params": merge_params(weights, alphas)}, x)
        return (jnp.argmax(logits, -1) == y).mean()

    return jax.jit(evaluate)


class DartsSearch:
    """Alternating bilevel optimization driver (run_trial.py train loop)."""

    def __init__(
        self,
        primitives: Sequence[str],
        num_layers: int = 8,
        settings: Optional[Dict[str, Any]] = None,
        input_channels: int = 3,
        num_classes: int = 10,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: int = 0,
    ):
        s = dict(settings or {})
        self.num_epochs = int(s.get("num_epochs", 50) or 50)
        self.w_lr = float(s.get("w_lr", 0.025))
        self.w_lr_min = float(s.get("w_lr_min", 0.001))
        self.w_momentum = float(s.get("w_momentum", 0.9))
        self.w_weight_decay = float(s.get("w_weight_decay", 3e-4))
        self.w_grad_clip = float(s.get("w_grad_clip", 5.0))
        self.alpha_lr = float(s.get("alpha_lr", 3e-4))
        self.alpha_weight_decay = float(s.get("alpha_weight_decay", 1e-3))
        self.batch_size = int(s.get("batch_size", 128) or 128)
        self.init_channels = int(s.get("init_channels", 16))
        self.num_nodes = int(s.get("num_nodes", 4))
        self.stem_multiplier = int(s.get("stem_multiplier", 3))
        self.print_step = int(s.get("print_step", 50))
        # Cosine-schedule horizon override: decouples the lr schedule (and
        # with it the _compiled_search_step cache key, which is static in
        # total_steps) from the actual demo length — a short evidence run
        # pinned to a reference horizon reuses the exact compiled program of
        # a full-length run instead of paying a fresh multi-minute XLA
        # compile for a different schedule constant.
        self.schedule_horizon = int(s.get("schedule_horizon", 0) or 0)
        # "jvp" (exact, default) | "fd" (reference central-difference parity).
        # Normalize + fail fast here: HPO assignments bypass the suggester's
        # validate_algorithm_settings, and a bad value would otherwise only
        # raise at the first jitted step, after dataset load and model init.
        self.hessian_mode = str(s.get("hessian_mode", "jvp") or "jvp").strip().lower()
        if self.hessian_mode not in ("jvp", "fd"):
            raise ValueError(
                f"hessian_mode must be 'jvp' or 'fd', got {s.get('hessian_mode')!r}"
            )
        # settings arrive as strings from HPO assignments: explicit opt-in
        remat = str(s.get("remat_cells", "")).strip().lower() in ("1", "true", "yes", "on")

        prims = list(primitives)
        if "none" not in prims:
            prims.append("none")  # search_space.py appends 'none'
        self.primitives = prims
        self.model = DartsSupernet(
            primitives=tuple(prims),
            init_channels=self.init_channels,
            input_channels=input_channels,
            num_classes=num_classes,
            num_layers=num_layers,
            num_nodes=self.num_nodes,
            stem_multiplier=self.stem_multiplier,
            remat_cells=remat,
        )
        self.mesh = mesh
        self.seed = seed
        self._built = False

    # ------------------------------------------------------------------

    def build(self, sample_shape: Tuple[int, ...], total_steps: int) -> None:
        from ..utils.modelinit import jitted_init

        key = jax.random.PRNGKey(self.seed)
        params = jitted_init(self.model, key, jnp.zeros((2,) + tuple(sample_shape)))
        self.weights, self.alphas = split_params(params)

        self.total_steps = max(self.schedule_horizon or total_steps, 1)
        self.w_opt_state = _make_w_tx(
            self.w_weight_decay, self.w_momentum, self.w_lr, self.w_grad_clip
        ).init(self.weights)
        self.a_opt_state = _make_a_tx(
            self.alpha_weight_decay, self.alpha_lr
        ).init(self.alphas)
        self.step_idx = 0

        # Traced hyperparameters: HPO trials over these share one compiled
        # program (the values are runtime scalars, not HLO constants).
        self.hyper = {
            "w_lr": jnp.float32(self.w_lr),
            "w_momentum": jnp.float32(self.w_momentum),
            "w_weight_decay": jnp.float32(self.w_weight_decay),
            "alpha_lr": jnp.float32(self.alpha_lr),
            "alpha_weight_decay": jnp.float32(self.alpha_weight_decay),
        }

        if self.mesh is not None:
            # Data-parallel bilevel search (SURVEY §7 hard part 1): supernet
            # weights, alphas, and optimizer state are explicitly replicated
            # over the mesh while _epoch_iter shards batches over 'data' —
            # GSPMD then all-reduces both the weight grads and the
            # Hessian-vector terms of the alpha grads, with no
            # involuntary resharding of the replicated state.
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self.mesh, P())
            (self.weights, self.alphas, self.w_opt_state, self.a_opt_state) = (
                jax.device_put(
                    (self.weights, self.alphas, self.w_opt_state, self.a_opt_state),
                    replicated,
                )
            )

        self._search_step = _compiled_search_step(
            self.model, self.total_steps, self.w_lr_min, self.w_grad_clip,
            self.hessian_mode,
        )
        self._eval_step = _compiled_eval_step(self.model)
        self._built = True

    def _epoch_iter(self, x, y, rng):
        """Epoch iterator with batches staged on device ahead of use
        (double buffering — katib_tpu.utils.prefetch). Meshed runs stage with
        the data-parallel sharding; single-device runs stay uncommitted
        (committed arrays dispatch slowly on tunneled backends)."""
        from ..utils.prefetch import prefetch_to_device

        base = [(x, y)] if len(x) < self.batch_size else batches(
            x, y, self.batch_size, rng
        )
        sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self.mesh, P("data"))
        return prefetch_to_device(base, sharding=sharding)

    # ------------------------------------------------------------------

    def train_epoch(self, train_data, valid_data, rng: np.random.Generator):
        """One epoch of alternating updates (run_trial.py train())."""
        assert self._built
        x_t, y_t = train_data
        x_v, y_v = valid_data
        losses = []
        train_iter = self._epoch_iter(x_t, y_t, rng)
        valid_iter = self._epoch_iter(x_v, y_v, rng)
        for train_batch in train_iter:
            try:
                valid_batch = next(valid_iter)
            except StopIteration:
                valid_iter = self._epoch_iter(x_v, y_v, rng)
                valid_batch = next(valid_iter)
            (self.weights, self.alphas, self.w_opt_state, self.a_opt_state, loss) = (
                self._search_step(
                    self.weights,
                    self.alphas,
                    self.w_opt_state,
                    self.a_opt_state,
                    self.step_idx,
                    self.hyper,
                    train_batch,
                    valid_batch,
                )
            )
            self.step_idx += 1
            losses.append(loss)
        return float(jnp.stack(losses).mean())

    def validate(self, valid_data, rng: np.random.Generator, max_batches: int = 50) -> float:
        x_v, y_v = valid_data
        accs = []
        for i, batch in enumerate(self._epoch_iter(x_v, y_v, rng)):
            if i >= max_batches:
                break
            accs.append(self._eval_step(self.weights, self.alphas, batch))
        return float(jnp.stack(accs).mean()) if accs else 0.0

    def genotype(self) -> Dict[str, Any]:
        params = merge_params(self.weights, self.alphas)
        return genotype(params, self.primitives, self.num_nodes)


def _search_and_report(search: DartsSearch, train_data, valid_data, ctx) -> float:
    """Shared epoch loop: alternate bilevel updates, validate, report
    per-epoch metrics (run_trial.py train loop + print format)."""
    rng = np.random.default_rng(0)
    best_acc = 0.0
    for _epoch in range(search.num_epochs):
        loss = search.train_epoch(train_data, valid_data, rng)
        acc = search.validate(valid_data, rng)
        best_acc = max(best_acc, acc)
        if ctx is not None:
            ctx.report(**{"Validation-accuracy": acc, "Train-loss": loss})
        else:
            print(f"Validation-accuracy={acc}")
            print(f"Train-loss={loss}")
    return best_acc


DARTS_HPO_DEFAULT_PRIMITIVES = (
    "separable_convolution_3x3",
    "max_pooling_3x3",
    "skip_connection",
)


def run_darts_hpo_trial(assignments: Dict[str, str], ctx=None, **overrides) -> None:
    """HPO entry point: assignments are individual DartsSearch settings
    (w_lr, alpha_lr, w_momentum, ...) from an HPO suggester (tpe/random/...),
    not the darts suggester's config payload. This is the reference's
    pytorch-mnist-style HPO matrix applied to the DARTS workload — and
    because optimizer hyperparameters are traced (see DartsSearch), every
    trial of the sweep reuses one compiled search step."""
    settings: Dict[str, Any] = dict(assignments)
    settings.update(overrides)
    num_layers = int(settings.pop("num_layers", 3))
    primitives = settings.pop("primitives", list(DARTS_HPO_DEFAULT_PRIMITIVES))
    n_train = int(settings.pop("num_train_examples", 0) or 0) or None
    mesh = None
    if ctx is not None and len(ctx.jax_devices()) > 1:
        mesh = ctx.mesh(axis_names=("data",))

    x, y = load_cifar10("train", n=n_train)
    half = len(x) // 2
    train_data, valid_data = (x[:half], y[:half]), (x[half:], y[half:])

    search = DartsSearch(
        primitives=primitives, num_layers=num_layers, settings=settings, mesh=mesh
    )
    steps_per_epoch = max(half // search.batch_size, 1)
    search.build(x.shape[1:], steps_per_epoch * search.num_epochs)
    best_acc = _search_and_report(search, train_data, valid_data, ctx)
    print(f"Best-accuracy={best_acc}")


def run_darts_trial_scaled(assignments: Dict[str, str], ctx=None, **overrides) -> None:
    """run_darts_trial with algorithm-settings overrides merged in — the
    single place that re-encodes the suggester's settings payload (used by
    CI-scale tests and the bench e2e stage)."""
    settings = json.loads(assignments["algorithm-settings"].replace("'", '"'))
    settings.update(overrides)
    assignments = dict(assignments)
    assignments["algorithm-settings"] = json.dumps(settings)
    run_darts_trial(assignments, ctx)


def run_darts_trial(assignments: Dict[str, str], ctx=None) -> None:
    """Trial entry point — parses the DARTS suggestion assignments
    (run_trial.py main argument parsing) and runs the search, reporting
    Best-Genotype + validation accuracy per epoch."""
    settings = json.loads(assignments["algorithm-settings"].replace("'", '"'))
    search_space = json.loads(assignments["search-space"].replace("'", '"'))
    num_layers = int(assignments["num-layers"])

    # dataset size / epochs can be trimmed via settings for CI-scale runs
    n_train = int(settings.get("num_train_examples", 0) or 0) or None
    mesh = None
    if ctx is not None and len(ctx.jax_devices()) > 1:
        mesh = ctx.mesh(axis_names=("data",))

    x, y = load_cifar10("train", n=n_train)
    half = len(x) // 2
    train_data, valid_data = (x[:half], y[:half]), (x[half:], y[half:])

    search = DartsSearch(
        primitives=search_space,
        num_layers=num_layers,
        settings=settings,
        mesh=mesh,
    )
    steps_per_epoch = max(half // search.batch_size, 1)
    search.build(x.shape[1:], steps_per_epoch * search.num_epochs)
    best_acc = _search_and_report(search, train_data, valid_data, ctx)
    gene = search.genotype()
    # reference run_trial.py prints the best accuracy + genotype at the end
    print(f"Best-accuracy={best_acc}")
    print(f"Best-Genotype={gene}")
