"""HTTP/JSON wire protocol — the sharded control plane's transport.

The gRPC plane (service/rpc.py) already mirrors the reference's
``api.proto`` method surface as JSON payloads; this module serves the SAME
:class:`~.rpc.ApiServicer` handlers over plain HTTP/JSON using the
zero-dependency ThreadingHTTPServer pattern (and bearer-token auth) of
``ui/server.py``, so a replica needs nothing beyond the standard library to
expose its Suggestion / EarlyStopping / DBManager services:

    POST /rpc/<Method>                 api.proto method, JSON body -> JSON
    GET  /replica/status               replica identity + claimed experiments
    GET  /replica/experiments/<name>   experiment status (owner's live view)
    POST /replica/experiments          create + claim + run a spec   [auth]
    GET  /metrics                      Prometheus text exposition

Method names are exactly the :attr:`ApiServicer.METHODS` keys (plus the
batched ``ReportManyObservationLogs``); each is attributed to its api.proto
service for the ``katib_rpc_requests_total`` / ``katib_rpc_latency_seconds``
``{service=}`` series. Every ``/rpc`` call is a POST (even reads — the
payload is a JSON document, the gRPC convention), authenticated by the same
bearer token as the replica-plane writes when one is configured.

The client half mirrors the reference suggestion-client retry policy
(consts/const.go DefaultGRPCRetryAttempts/Period) with exponential backoff:
connection errors and 5xx are retried, 4xx propagate immediately —
:class:`HttpApiClient`, :class:`HttpRemoteObservationStore` (with a batched
``report_many``), and the ``report_metrics`` env binding
(``KATIB_TPU_RPC_URL`` / ``KATIB_TPU_RPC_TOKEN``, runtime/metrics.py) all
ride it.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple
from urllib.parse import unquote, urlparse

from ..db.store import MetricLog, ObservationStore
from .rpc import ApiServicer

log = logging.getLogger("katib_tpu.httpapi")

ENV_RPC_URL = "KATIB_TPU_RPC_URL"
ENV_RPC_TOKEN = "KATIB_TPU_RPC_TOKEN"

# rpc methods a trial-writer scoped token may call (service/tenancy.py):
# the observation report/read verbs a trial process needs. Everything
# else — suggestions, early stopping, truncate/delete — is admin-scoped.
_WRITER_METHODS = frozenset(
    (
        "ReportObservationLog",
        "ReportManyObservationLogs",
        "GetObservationLog",
        "GetFoldedObservation",
    )
)


def _rpc_resources(method: str, payload: Dict) -> List[str]:
    """The tenant-owned resource names a method touches — trial names carry
    their experiment's tenant prefix (suggest/base.py trial naming), so
    ownership of every row reduces to a name check."""
    if method == "ReportManyObservationLogs":
        return [
            str(e.get("trialName", ""))
            for e in payload.get("entries", [])
            if isinstance(e, dict)
        ]
    if "trialName" in payload:
        return [str(payload["trialName"])]
    exp = payload.get("experiment")
    if isinstance(exp, dict) and exp.get("name"):
        return [str(exp["name"])]
    return []


# api.proto service attribution for the {service=} metric labels
_METHOD_SERVICE: Dict[str, str] = {
    "GetSuggestions": "Suggestion",
    "ValidateAlgorithmSettings": "Suggestion",
    "GetEarlyStoppingRules": "EarlyStopping",
    "ValidateEarlyStoppingSettings": "EarlyStopping",
    "SetTrialStatus": "EarlyStopping",
    "ReportObservationLog": "DBManager",
    "ReportManyObservationLogs": "DBManager",
    "GetObservationLog": "DBManager",
    "GetFoldedObservation": "DBManager",
    "TruncateObservationLog": "DBManager",
    "DeleteObservationLog": "DBManager",
}


class RpcError(RuntimeError):
    """Wire-level failure after retries, or a non-retryable status."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


class _ApiHandler(BaseHTTPRequestHandler):
    servicer: ApiServicer = None        # injected by serve_api
    controller = None                   # optional: replica-plane endpoints
    replica_manager = None              # optional: claim/run hooks
    metrics = None                      # optional MetricsRegistry
    auth_token: Optional[str] = None    # None disables auth entirely
    tenants = None                      # TenantRegistry; None = tenancy off
    admission = None                    # AdmissionLimiter (set with tenants)
    # distributed tracing plane (ISSUE 19) — all off by default so the
    # knob-off wire stays byte-identical to the PR 17 plane
    wire_tracing: bool = False          # runtime.wire_tracing
    slo: Dict[str, float] = {}          # method -> latency objective seconds
    flight = None                       # FlightRecorder (slow-RPC ring)
    root_dir: Optional[str] = None      # shared state root (fleet fan-out)
    replica_name: str = ""              # span attr for server-side spans

    # HTTP/1.1 => persistent connections: a trial process's pooled client
    # reuses one socket per replica instead of paying a TCP handshake per
    # group-commit batch. _send always sets Content-Length, which keep-alive
    # requires; idle connections are reaped by the handler timeout.
    protocol_version = "HTTP/1.1"
    timeout = 60.0

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, payload: Any, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _peer_token(self) -> str:
        supplied = self.headers.get("X-Katib-Token", "")
        auth = self.headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            supplied = auth[len("Bearer "):]
        return supplied

    def _authorized(self) -> bool:
        if self.auth_token is None:
            return True
        import secrets

        return secrets.compare_digest(
            self._peer_token().encode("utf-8", "replace"), self.auth_token.encode()
        )

    def _identity(self):
        """Tenancy-mode identity resolution (service/tenancy.py): the
        global token is the break-glass admin, tenant tokens resolve at
        their minted scope, no-token is break-glass only when no global
        token is configured. None = reject. Only consulted when a
        TenantRegistry is bound."""
        from .tenancy import resolve_wire_identity

        return resolve_wire_identity(
            self.tenants, self.auth_token, self._peer_token()
        )

    def _deny_tenant(self, tenant: Optional[str], plane: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                "katib_tenant_denied_total",
                tenant=tenant or "(unresolved)", plane=plane,
            )

    def _record(self, service: str, method: str, t0: float, code: int) -> None:
        dt = time.perf_counter() - t0
        tenant = getattr(self, "_req_tenant", "") or "default"
        if self.metrics is not None:
            self.metrics.inc(
                "katib_rpc_requests_total",
                service=service, method=method, code=str(code),
            )
            if self.wire_tracing:
                # per-tenant SLO series (ISSUE 19): the latency histogram
                # grows tenant=/method= labels, and a configurable objective
                # feeds the violation counter. Knob off keeps the PR 17
                # exposition byte-identical.
                self.metrics.observe(
                    "katib_rpc_latency_seconds", dt,
                    service=service, method=method, tenant=tenant,
                )
                objective = self.slo.get(method, self.slo.get("default"))
                if objective is not None and dt > objective:
                    self.metrics.inc(
                        "katib_slo_violations_total",
                        tenant=tenant, method=method,
                    )
            else:
                self.metrics.observe(
                    "katib_rpc_latency_seconds", dt, service=service,
                )
        span = getattr(self, "_req_span", None)
        tracer = getattr(self, "_req_tracer", None)
        if span is not None and tracer is not None:
            tracer.end_span(span, code=code, tenant=tenant)
        if self.flight is not None:
            spans = []
            if span is not None and tracer is not None:
                spans = [
                    s.to_dict()
                    for s in tracer.trace_spans("_rpc", span.trace_id)
                ]
            self.flight.record(
                method, dt, tenant=tenant,
                trace_id=span.trace_id if span is not None else "",
                code=code, spans=spans,
            )

    def _tracer(self):
        """The controller's tracer (wire-sink attached) when bound, else the
        process tracer — server-side rpc spans must not vanish on a
        servicer-only deployment."""
        ctrl = self.controller
        if ctrl is not None and getattr(ctrl, "tracer", None) is not None:
            return ctrl.tracer
        from ..tracing import default_tracer

        return default_tracer()

    def _wire_trace_ctx(self) -> Optional[Tuple[str, str]]:
        """(trace_id, parent_id) from X-Katib-Traceparent. Malformed,
        oversized or garbage values are ignored LOUDLY — a warning event —
        and the request is still served (never a 500)."""
        from ..tracing import (
            MAX_TRACEPARENT_LEN, WIRE_TRACEPARENT_HEADER, parse_traceparent,
        )

        raw = self.headers.get(WIRE_TRACEPARENT_HEADER)
        if raw is None:
            return None
        if len(raw) > MAX_TRACEPARENT_LEN:
            self._trace_ctx_warn(f"oversized ({len(raw)} bytes)")
            return None
        ctx = parse_traceparent(raw)
        if ctx is None:
            self._trace_ctx_warn(f"malformed {raw[:64]!r}")
            return None
        return ctx

    def _trace_ctx_warn(self, why: str) -> None:
        ctrl = self.controller
        events = getattr(ctrl, "events", None) if ctrl is not None else None
        if events is not None:
            events.event(
                "_wire", "Rpc", self.replica_name or "api",
                "TraceContextInvalid",
                f"ignoring invalid wire trace context: {why}",
                warning=True,
            )
        else:
            log.warning("ignoring invalid wire trace context: %s", why)

    # -- /rpc dispatch -------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        path = unquote(urlparse(self.path).path).rstrip("/")
        # per-request scratch consumed by _record (instance-per-connection,
        # requests on one keep-alive socket are sequential)
        self._req_tenant = ""
        self._req_span = None
        self._req_tracer = None
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length).decode() if length else ""
            if path.startswith("/rpc/"):
                return self._rpc(path[len("/rpc/"):], body)
            if path == "/replica/experiments":
                return self._create_experiment(body)
            return self._send({"error": "not found"}, code=404)
        except Exception as e:  # pragma: no cover - defensive
            return self._send({"error": f"{type(e).__name__}: {e}"}, code=500)
        finally:
            # an exception path that skipped _record must still close the span
            if self._req_span is not None and self._req_tracer is not None:
                self._req_tracer.end_span(self._req_span)
            self._req_span = self._req_tracer = None

    def _rpc(self, method: str, body: str) -> None:
        t0 = time.perf_counter()
        if self.wire_tracing:
            # server-side rpc span, parented under the caller's wire context
            # when the X-Katib-Traceparent header carries a valid one
            tracer = self._tracer()
            ctx = self._wire_trace_ctx()
            if tracer is not None and tracer.enabled:
                trace_id, parent_id = ctx if ctx else (tracer.new_trace_id(), None)
                self._req_tracer = tracer
                self._req_span = tracer.start_span(
                    f"rpc.{method}", "_rpc", trace_id, parent_id,
                    attrs={"method": method, "replica": self.replica_name},
                )
        service = _METHOD_SERVICE.get(method, "Api")
        fn = ApiServicer.METHODS.get(method)
        if fn is None:
            self._record(service, method, t0, 404)
            return self._send({"error": f"unknown method {method!r}"}, code=404)
        ident = None
        if self.tenants is None:
            if not self._authorized():
                self._record(service, method, t0, 403)
                return self._send({"error": "missing or invalid auth token"}, code=403)
        else:
            ident = self._identity()
            if ident is None:
                self._deny_tenant(None, "json")
                self._record(service, method, t0, 403)
                return self._send({"error": "missing or invalid auth token"}, code=403)
            self._req_tenant = ident.tenant or ""
        try:
            payload = json.loads(body) if body else {}
            if ident is not None:
                err = self._tenant_gate(ident, method, payload)
                if err is not None:
                    self._record(service, method, t0, 403)
                    return self._send(err, code=403)
            reply = fn(self.servicer, payload)
        except (ValueError, KeyError) as e:
            self._record(service, method, t0, 400)
            return self._send({"error": f"{type(e).__name__}: {e}"}, code=400)
        except Exception as e:
            self._record(service, method, t0, 500)
            return self._send({"error": f"{type(e).__name__}: {e}"}, code=500)
        self._record(service, method, t0, 200)
        return self._send(reply)

    def _tenant_gate(self, ident, method: str, payload: Dict) -> Optional[Dict]:
        """Scope + namespace enforcement for one tenancy-mode rpc: an error
        envelope (sent as 403) or None when admitted. Every resource the
        method touches must live inside the caller's namespace — including
        each entry of a ReportManyObservationLogs batch."""
        from .tenancy import SCOPE_ADMIN

        if method not in _WRITER_METHODS and not ident.allows(SCOPE_ADMIN):
            self._deny_tenant(ident.tenant, "json")
            return {
                "error": f"scope {ident.scope!r} cannot call {method}",
                "tenant": ident.tenant,
            }
        for name in _rpc_resources(method, payload):
            if name and not ident.owns(name):
                self._deny_tenant(ident.tenant, "json")
                return {
                    "error": (
                        f"tenant {ident.tenant!r} does not own {name!r}"
                        if ident.tenant
                        else f"token does not grant access to {name!r}"
                    ),
                    "tenant": ident.tenant,
                }
        if self.metrics is not None and ident.tenant:
            self.metrics.inc("katib_tenant_requests_total", tenant=ident.tenant)
        return None

    # -- replica plane -------------------------------------------------------

    def _quota_refused(self, tenant: str, name: str, why: str):
        if self.metrics is not None:
            self.metrics.inc("katib_tenant_quota_refusals_total", tenant=tenant)
        ctrl = self.controller
        if ctrl is not None and getattr(ctrl, "events", None) is not None:
            ctrl.events.event(
                name, "Tenant", tenant, "TenantQuotaRefused",
                f"tenant {tenant} refused admission for {name}: {why}",
                warning=True,
            )
        return {
            "error": f"tenant {tenant!r} quota refused for {name!r}: {why}",
            "tenant": tenant,
        }, 429

    def _tenant_admit_spec(self, ident, spec):
        """Tenancy-mode admission for one experiment spec: namespace the
        name under the caller's tenant, refuse quota overruns with a
        tenant-tagged 429, and compile the tenant's quota envelope down
        onto the fair-share engine (``fair_share_weight``,
        ``device_quota`` — PR 2) before the replica claims capacity
        (PR 15). Returns (error_payload, http_code) or None to admit;
        break-glass admins pass through untouched."""
        from . import tenancy as tn

        if not ident.allows(tn.SCOPE_ADMIN):
            self._deny_tenant(ident.tenant, "json")
            return {
                "error": f"scope {ident.scope!r} cannot create experiments",
                "tenant": ident.tenant,
            }, 403
        if ident.tenant is None:
            return None
        owner = tn.tenant_of(spec.name)
        if owner is None:
            spec.name = tn.namespaced(ident.tenant, spec.name)
        elif owner != ident.tenant:
            self._deny_tenant(ident.tenant, "json")
            return {
                "error": f"tenant {ident.tenant!r} cannot create {spec.name!r} "
                         f"(namespace owned by {owner!r})",
                "tenant": ident.tenant,
            }, 403
        rec = self.tenants.load(ident.tenant)
        if rec is None:
            return None
        if self.admission is not None and not self.admission.allow(
            ident.tenant, rec.admission_per_minute
        ):
            return self._quota_refused(
                ident.tenant, spec.name,
                f"admission rate {rec.admission_per_minute:g}/min exceeded",
            )
        if rec.max_experiments > 0:
            live = tn.claimed_experiments(self.tenants.root_dir, ident.tenant)
            if spec.name not in live and len(live) >= rec.max_experiments:
                return self._quota_refused(
                    ident.tenant, spec.name,
                    f"{len(live)}/{rec.max_experiments} concurrent experiments "
                    "already placed",
                )
        if rec.fair_share_weight != 1.0:
            spec.fair_share_weight = rec.fair_share_weight
        if rec.device_quota is not None:
            res = getattr(spec.trial_template, "resources", None)
            if res is not None:
                dq = getattr(res, "device_quota", None)
                res.device_quota = (
                    rec.device_quota if dq is None else min(dq, rec.device_quota)
                )
        return None

    def _create_experiment(self, body: str) -> None:
        t0 = time.perf_counter()
        ident = None
        if self.tenants is None:
            if not self._authorized():
                self._record("Replica", "CreateExperiment", t0, 403)
                return self._send({"error": "missing or invalid auth token"}, code=403)
        else:
            ident = self._identity()
            if ident is None:
                self._deny_tenant(None, "json")
                self._record("Replica", "CreateExperiment", t0, 403)
                return self._send({"error": "missing or invalid auth token"}, code=403)
            self._req_tenant = ident.tenant or ""
        ctrl, mgr = self.controller, self.replica_manager
        if ctrl is None or mgr is None:
            self._record("Replica", "CreateExperiment", t0, 404)
            return self._send(
                {"error": "no controller bound (servicer-only endpoint)"}, code=404
            )
        from ..api.spec import experiment_spec_from_mapping, parse_spec_document

        try:
            payload = parse_spec_document(body)
            if not isinstance(payload, dict):
                raise ValueError("spec body must be a JSON or YAML mapping")
            spec = experiment_spec_from_mapping(payload)
        except Exception as e:
            self._record("Replica", "CreateExperiment", t0, 400)
            return self._send({"error": f"{type(e).__name__}: {e}"}, code=400)
        if ident is not None:
            refused = self._tenant_admit_spec(ident, spec)
            if refused is not None:
                err, code = refused
                self._record("Replica", "CreateExperiment", t0, code)
                return self._send(err, code=code)
        if not mgr.claim_new(spec.name):
            # at capacity (or the experiment is already placed elsewhere):
            # the client router retries against another replica
            self._record("Replica", "CreateExperiment", t0, 429)
            return self._send(
                {"error": f"replica {mgr.replica_id!r} cannot claim "
                          f"{spec.name!r} (capacity {mgr.capacity})"},
                code=429,
            )
        try:
            ctrl.create_experiment(spec)
            mgr.run_experiment(spec.name)
        except Exception as e:
            mgr.release(spec.name)
            self._record("Replica", "CreateExperiment", t0, 400)
            return self._send({"error": f"{type(e).__name__}: {e}"}, code=400)
        self._record("Replica", "CreateExperiment", t0, 201)
        return self._send(
            {"created": spec.name, "replica": mgr.replica_id}, code=201
        )

    def do_GET(self) -> None:  # noqa: N802
        path = unquote(urlparse(self.path).path).rstrip("/")
        try:
            if path == "/metrics" and self.metrics is not None:
                body = self.metrics.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/api/fleet/slow":
                if self.flight is None:
                    return self._send(
                        {"error": "flight recorder off (runtime.wire_tracing "
                                  "disabled or slow_rpc_ring=0)"}, code=404
                    )
                return self._send({"slow": self.flight.dump()})
            if path == "/api/fleet":
                root = self.root_dir or getattr(self.controller, "root_dir", None)
                if not root:
                    return self._send(
                        {"error": "no shared state root bound"}, code=404
                    )
                if self.tenants is not None:
                    from .tenancy import SCOPE_ADMIN

                    ident = self._identity()
                    if ident is None or not ident.allows(SCOPE_ADMIN):
                        self._deny_tenant(
                            ident.tenant if ident else None, "json"
                        )
                        return self._send(
                            {"error": "fleet view requires an admin token"},
                            code=403,
                        )
                return self._send(fleet_snapshot(root, token=self.auth_token))
            ident = None
            if self.tenants is not None and path.startswith("/replica/"):
                # router views are tenant-scoped too: a tenant token sees
                # only its own placements, never another namespace's names
                ident = self._identity()
                if ident is None:
                    self._deny_tenant(None, "json")
                    return self._send(
                        {"error": "missing or invalid auth token"}, code=403
                    )
            mgr = self.replica_manager
            if path == "/replica/status" and mgr is not None:
                doc = mgr.status()
                if ident is not None and ident.tenant is not None:
                    doc = dict(doc)
                    doc["claimed"] = [
                        n for n in doc.get("claimed", []) if ident.owns(n)
                    ]
                return self._send(doc)
            parts = path.split("/")
            if (
                len(parts) == 4
                and parts[1] == "replica"
                and parts[2] == "experiments"
                and self.controller is not None
            ):
                if ident is not None and not ident.owns(parts[3]):
                    self._deny_tenant(ident.tenant, "json")
                    return self._send(
                        {
                            "error": f"tenant {ident.tenant!r} does not own "
                                     f"{parts[3]!r}",
                            "tenant": ident.tenant,
                        },
                        code=403,
                    )
                exp = self.controller.state.get_experiment(parts[3])
                if exp is None:
                    return self._send(
                        {"error": f"experiment {parts[3]!r} not placed here"},
                        code=404,
                    )
                return self._send(exp.to_dict())
            return self._send({"error": "not found"}, code=404)
        except Exception as e:  # pragma: no cover - defensive
            return self._send({"error": f"{type(e).__name__}: {e}"}, code=500)


class _KeepAliveHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that force-closes accepted keep-alive sockets on
    ``server_close()``. Stock ThreadingHTTPServer only closes the LISTEN
    socket, so with HTTP/1.1 persistent connections a logically-stopped
    server would keep answering pooled clients through its still-open
    handler threads — a restarted replica on the same port must not share
    the wire with its corpse."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._live_requests = set()
        self._live_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._live_lock:
            self._live_requests.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._live_lock:
            self._live_requests.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._live_lock:
            live = list(self._live_requests)
            self._live_requests.clear()
        for sock in live:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


def serve_api(
    servicer: ApiServicer,
    host: str = "127.0.0.1",
    port: int = 0,
    controller=None,
    replica_manager=None,
    metrics=None,
    auth_token: Optional[str] = None,
    tenants=None,
    block: bool = False,
    wire_tracing: bool = False,
    slo_objectives: str = "",
    slow_rpc_ring: int = 32,
    root_dir: Optional[str] = None,
    replica_name: str = "",
) -> ThreadingHTTPServer:
    """Start the HTTP/JSON api server; returns the ThreadingHTTPServer with
    ``.bound_port`` and ``.base_url`` set (port=0 lets the OS pick).
    ``tenants`` (a TenantRegistry) switches the wire into tenancy mode:
    every request resolves to an identity, namespaces are enforced, and
    experiment admission honors per-tenant quotas. ``wire_tracing`` arms the
    distributed tracing plane (ISSUE 19): server-side rpc spans from the
    X-Katib-Traceparent header, per-tenant SLO series, and the slow-RPC
    flight recorder (``slow_rpc_ring`` worst requests, GET /api/fleet/slow)."""
    admission = None
    if tenants is not None:
        from .tenancy import AdmissionLimiter

        # replica-shared bucket files under the tenants dir: a refusal on
        # one replica cannot be laundered by retrying against another
        admission = AdmissionLimiter(shared_dir=tenants.dir)
    flight = None
    slo: Dict[str, float] = {}
    if wire_tracing:
        from ..tracing import FlightRecorder, parse_slo_objectives

        slo = parse_slo_objectives(slo_objectives)
        if slow_rpc_ring > 0:
            flight = FlightRecorder(slow_rpc_ring)
    handler = type(
        "BoundApiHandler",
        (_ApiHandler,),
        {
            "servicer": servicer,
            "controller": controller,
            "replica_manager": replica_manager,
            "metrics": metrics,
            "auth_token": auth_token,
            "tenants": tenants,
            "admission": admission,
            "wire_tracing": wire_tracing,
            "slo": slo,
            "flight": flight,
            "root_dir": root_dir,
            "replica_name": replica_name,
        },
    )
    httpd = _KeepAliveHTTPServer((host, port), handler)
    httpd.bound_port = httpd.server_address[1]
    httpd.base_url = f"http://{host}:{httpd.bound_port}"
    httpd.auth_token = auth_token
    httpd.flight = flight
    if block:
        httpd.serve_forever()
    else:
        t = threading.Thread(
            target=httpd.serve_forever, daemon=True, name="katib-rpc-http"
        )
        t.start()
    return httpd


# -- fleet status plane (ISSUE 19) -------------------------------------------

# the metric families the fleet table folds per replica: total rpc traffic,
# ingest plane activity, and the per-tenant SLO standing
_FLEET_COUNTER_FAMILIES = (
    "katib_rpc_requests_total",
    "katib_ingest_frames_total",
)


# per-experiment step-statistics families folded into the fleet row's
# ``perf`` map (ISSUE 20; absent entirely when runtime.step_stats is off)
_FLEET_PERF_FAMILIES = {
    "katib_step_seconds": "stepSeconds",
    "katib_trial_throughput": "throughput",
    "katib_trial_mfu_ratio": "mfu",
    "katib_trial_retraces_total": "retraces",
    "katib_objective_per_device_second": "objectivePerDeviceSecond",
}


def _parse_labels(head: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    if "{" in head:
        for part in head[head.index("{") + 1:-1].split(","):
            k, _, v = part.partition("=")
            if k:
                labels[k.strip()] = v.strip().strip('"')
    return labels


def _metrics_summary(text: str) -> Dict[str, Any]:
    """Fold one replica's Prometheus exposition into the fleet row: summed
    rpc/ingest counters, the last coalesce depth, per-tenant SLO violation
    counts, and the per-experiment step-performance rollups. Tolerant of
    any families it doesn't know."""
    sums: Dict[str, float] = {}
    slo: Dict[str, float] = {}
    perf: Dict[str, Dict[str, Any]] = {}
    depth: Optional[float] = None
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        head, _, raw = line.rpartition(" ")
        if not head:
            continue
        try:
            value = float(raw)
        except ValueError:
            continue
        name = head.split("{", 1)[0]
        if name in _FLEET_COUNTER_FAMILIES:
            sums[name] = sums.get(name, 0.0) + value
        elif name == "katib_ingest_coalesce_depth":
            depth = value
        elif name == "katib_slo_violations_total":
            tenant = _parse_labels(head).get("tenant", "default")
            slo[tenant] = slo.get(tenant, 0.0) + value
        elif name in _FLEET_PERF_FAMILIES:
            labels = _parse_labels(head)
            exp = labels.get("experiment")
            if not exp:
                continue
            row = perf.setdefault(exp, {})
            if name == "katib_step_seconds":
                row[labels.get("quantile", "p50")] = value
            else:
                row[_FLEET_PERF_FAMILIES[name]] = value
    out: Dict[str, Any] = {
        "rpcRequests": sums.get("katib_rpc_requests_total", 0.0),
        "ingestFrames": sums.get("katib_ingest_frames_total", 0.0),
        "ingestCoalesceDepth": depth,
        "sloViolations": slo,
    }
    if perf:
        # key absent entirely when step stats are off — the fleet JSON stays
        # byte-identical to the pre-perf plane
        out["perf"] = perf
    return out


def _fetch_metrics_text(base_url: str, timeout: float) -> Optional[str]:
    try:
        with urllib.request.urlopen(
            base_url.rstrip("/") + "/metrics", timeout=timeout
        ) as resp:
            return resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError):
        return None


def fleet_snapshot(
    root_dir: str, token: Optional[str] = None, timeout: float = 5.0
) -> Dict[str, Any]:
    """One fleet-wide view (GET /api/fleet, ``katib-tpu fleet``): fan out to
    every registered replica (placement registry) and merge live status,
    /metrics, ingest depth, lease/claim state and tenant quota standing.
    Dead replicas stay in the table flagged ``alive: false`` — a fleet view
    that hides the corpse hides the incident."""
    from ..controller.placement import placement_table

    table = placement_table(root_dir)
    replicas: List[Dict[str, Any]] = []
    for rep in table.get("replicas", []):
        row: Dict[str, Any] = {
            "replica": rep.get("replica"),
            "alive": bool(rep.get("alive")),
            "pid": rep.get("pid"),
            "url": rep.get("url"),
            "ingest": rep.get("ingest"),
            "capacity": rep.get("capacity"),
            "claimed": list(rep.get("claimed", [])),
            "ageSeconds": rep.get("ageSeconds"),
            "failovers": None,
            "metrics": None,
        }
        if row["alive"] and row["url"]:
            client = HttpApiClient(
                row["url"], token=token, timeout=timeout, retries=1
            )
            st = client.replica_status()
            if st is not None:
                row["claimed"] = list(st.get("claimed", row["claimed"]))
                row["failovers"] = st.get("failovers")
                row["ingest"] = st.get("ingest", row["ingest"])
            text = _fetch_metrics_text(row["url"], timeout)
            if text is not None:
                row["metrics"] = _metrics_summary(text)
        replicas.append(row)
    tenants: List[Dict[str, Any]] = []
    if os.path.isdir(os.path.join(root_dir, "tenants")):
        from .tenancy import TenantRegistry, claimed_experiments

        for rec in TenantRegistry(root_dir).records():
            tenants.append(
                {
                    "tenant": rec.name,
                    "admissionPerMinute": rec.admission_per_minute,
                    "maxExperiments": rec.max_experiments,
                    "deviceQuota": rec.device_quota,
                    "fairShareWeight": rec.fair_share_weight,
                    "claimed": len(claimed_experiments(root_dir, rec.name)),
                }
            )
    return {
        "root": root_dir,
        "replicas": replicas,
        "leases": table.get("leases", []),
        "tenants": tenants,
    }


# -- client ------------------------------------------------------------------

# the reference retries every suggestion-client RPC 10x (rpc.py
# DEFAULT_RETRY_ATTEMPTS); over HTTP the fixed 3s period becomes a capped
# exponential backoff so a restarting replica is re-dialed quickly but a
# dead one doesn't burn 30s per call
DEFAULT_HTTP_RETRIES = 10
DEFAULT_BACKOFF_BASE_S = 0.05
DEFAULT_BACKOFF_CAP_S = 2.0

# -- persistent-connection pool ----------------------------------------------
# One idle-connection pool per (pid, netloc): HTTP/1.1 keep-alive lets a
# trial process reuse a socket across group-commit batches instead of paying
# TCP setup per request. Keyed by pid so a fork()ed child never inherits (and
# corrupts) its parent's sockets; capped so dozens of streamer threads don't
# hoard file descriptors.
_POOL_MAX_IDLE = 32
_POOL: Dict[Tuple[int, str], List[http.client.HTTPConnection]] = {}
_POOL_LOCK = threading.Lock()


def _pool_get(netloc: str) -> Optional[http.client.HTTPConnection]:
    with _POOL_LOCK:
        conns = _POOL.get((os.getpid(), netloc))
        if conns:
            return conns.pop()
    return None


def _pool_put(netloc: str, conn: http.client.HTTPConnection) -> None:
    with _POOL_LOCK:
        conns = _POOL.setdefault((os.getpid(), netloc), [])
        if len(conns) < _POOL_MAX_IDLE:
            conns.append(conn)
            return
    conn.close()  # pool full: don't hoard fds


class HttpApiClient:
    """JSON-over-HTTP client for :func:`serve_api`.

    Retry semantics: connection failures and 5xx responses are retried with
    exponential backoff (a replica restarting mid-experiment is re-dialed,
    exactly the UNAVAILABLE policy of the gRPC client); 4xx responses raise
    :class:`RpcError` immediately (validation errors must not be retried
    into duplicates — the DBManager receiver is idempotent for the one
    at-least-once write path, ReportObservationLog)."""

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        timeout: float = 30.0,
        retries: int = DEFAULT_HTTP_RETRIES,
        backoff_base: float = DEFAULT_BACKOFF_BASE_S,
        backoff_cap: float = DEFAULT_BACKOFF_CAP_S,
        wire_tracing: Optional[bool] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        if wire_tracing is None:
            from ..tracing import wire_tracing_from_env

            wire_tracing = wire_tracing_from_env()
        self.wire_tracing = bool(wire_tracing)
        parsed = urlparse(self.base_url)
        self._netloc = parsed.netloc
        self._path_prefix = parsed.path.rstrip("/")

    @staticmethod
    def _error_detail(raw: bytes) -> str:
        """The server's {"error": ...} field when the body is our JSON
        envelope, the raw body text otherwise — a proxy's HTML 502 page or a
        bare traceback must surface, not a JSONDecodeError masking it."""
        if not raw:
            return ""
        try:
            detail = json.loads(raw.decode())
            if isinstance(detail, dict) and "error" in detail:
                return str(detail["error"])
        except Exception:
            pass
        return raw.decode("utf-8", "replace").strip()

    def _post(self, path: str, payload: Dict) -> Dict:
        data = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.wire_tracing:
            # W3C-style context on every rpc POST (ISSUE 19); knob off sends
            # the exact PR 17 header set — byte-identical wire bytes
            from ..tracing import WIRE_TRACEPARENT_HEADER, current_traceparent

            tp = current_traceparent()
            if tp:
                headers[WIRE_TRACEPARENT_HEADER] = tp
        last: Optional[BaseException] = None
        for attempt in range(self.retries):
            conn = _pool_get(self._netloc)
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(
                    self._netloc, timeout=self.timeout
                )
            try:
                conn.request("POST", self._path_prefix + path, body=data,
                             headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                reusable = not resp.will_close
            except (http.client.HTTPException, ConnectionError,
                    TimeoutError, OSError) as e:
                conn.close()
                last = e
                # a pooled socket may have been reaped by the server's idle
                # timeout; its loss is expected — redial before backing off
                if not fresh:
                    continue
            else:
                if reusable:
                    _pool_put(self._netloc, conn)
                else:
                    conn.close()
                if resp.status < 400:
                    body = raw.decode()
                    return json.loads(body) if body else {}
                detail = self._error_detail(raw)
                if resp.status < 500:
                    raise RpcError(
                        f"{path} -> HTTP {resp.status}: {detail}",
                        code=resp.status,
                    ) from None
                last = RpcError(
                    f"{path} -> HTTP {resp.status}: {detail}", code=resp.status
                )
            if attempt < self.retries - 1:
                time.sleep(min(self.backoff_base * (2 ** attempt), self.backoff_cap))
        raise RpcError(
            f"{path} failed after {self.retries} attempt(s): {last}"
        ) from last

    def call(self, method: str, payload: Dict) -> Dict:
        """One api.proto method (an ApiServicer.METHODS key)."""
        return self._post(f"/rpc/{method}", payload)

    def create_experiment(self, spec_mapping: Dict) -> Dict:
        """Replica-plane create: the receiving replica claims the placement
        lease and runs the experiment. 429 (at capacity) raises RpcError
        with ``code=429`` so the router can try the next replica."""
        return self._post("/replica/experiments", spec_mapping)

    def experiment_status(self, name: str) -> Optional[Dict]:
        """The owner's live experiment view, or None when not placed here."""
        req = urllib.request.Request(
            f"{self.base_url}/replica/experiments/{name}", method="GET"
        )
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise RpcError(f"experiment_status -> HTTP {e.code}", code=e.code) from None
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError):
            return None

    def replica_status(self) -> Optional[Dict]:
        req = urllib.request.Request(f"{self.base_url}/replica/status", method="GET")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError):
            return None


class HttpRemoteObservationStore(ObservationStore):
    """ObservationStore over the HTTP DBManager — what a trial process on
    another host uses to push metric streams (the ``KATIB_TPU_RPC_URL``
    binding of report_metrics). ``report_many`` ships a whole group-commit
    batch as ONE request, so the buffered store's flusher pays one round
    trip per drained batch instead of one per trial."""

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        timeout: float = 30.0,
        retries: int = DEFAULT_HTTP_RETRIES,
    ):
        self.client = HttpApiClient(
            base_url, token=token, timeout=timeout, retries=retries
        )

    @staticmethod
    def _rows(logs: Sequence[MetricLog]) -> list:
        return [
            {"timestamp": l.timestamp, "metricName": l.metric_name, "value": l.value}
            for l in logs
        ]

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        from ..tracing import current_traceparent

        payload = {"trialName": trial_name, "metricLogs": self._rows(logs)}
        tp = current_traceparent()
        if tp:
            payload["traceparent"] = tp  # rejoined server-side (api servicer)
        self.client.call("ReportObservationLog", payload)

    def report_many(self, entries: Sequence) -> None:
        batch = [
            {"trialName": t, "metricLogs": self._rows(logs)}
            for t, logs in entries
            if logs
        ]
        if not batch:
            return
        payload: Dict[str, Any] = {"entries": batch}
        if self.client.wire_tracing:
            # batch-level context (ISSUE 19 — the group-commit path lost its
            # spans before this): the servicer fans it into every entry that
            # doesn't carry its own (rpc.report_many_observation_logs)
            from ..tracing import current_traceparent

            tp = current_traceparent()
            if tp:
                payload["traceparent"] = tp
        self.client.call("ReportManyObservationLogs", payload)

    def get_observation_log(
        self, trial_name, metric_name=None, start_time=None, end_time=None, limit=None
    ):
        out = self.client.call(
            "GetObservationLog",
            {
                "trialName": trial_name,
                "metricName": metric_name,
                "startTime": start_time,
                "endTime": end_time,
                "limit": limit,
            },
        )
        return [
            MetricLog(float(l["timestamp"]), l["metricName"], str(l["value"]))
            for l in out.get("metricLogs", [])
        ]

    def folded(self, trial_name, metric_names):
        from ..api.spec import Metric, Observation

        out = self.client.call(
            "GetFoldedObservation",
            {"trialName": trial_name, "metricNames": list(metric_names)},
        )
        return Observation(metrics=[Metric.from_dict(m) for m in out.get("metrics", [])])

    def truncate_observation_log(self, trial_name: str, after_time: float) -> int:
        out = self.client.call(
            "TruncateObservationLog",
            {"trialName": trial_name, "afterTime": after_time},
        )
        return int(out.get("dropped", 0))

    def delete_observation_log(self, trial_name: str) -> None:
        self.client.call("DeleteObservationLog", {"trialName": trial_name})
