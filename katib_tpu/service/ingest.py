"""High-throughput ingest plane — binary-framed observation streaming.

The JSON wire (service/httpapi.py) pays a full HTTP parse and a JSON
codec round trip on every ``ReportManyObservationLogs`` — the hottest RPC
in the system: one call per group-commit batch, from every trial process,
on every flush. Upstream Katib fronts exactly this path with a dedicated
DB-manager service (PAPER.md §1); the Podracer decoupling pattern
(arXiv:2104.06272) that already shapes ``BufferedObservationStore`` argues
the same for the wire: producers enqueue cheap frames, one drainer owns
the expensive work. This module is that plane, three layers:

- **codec** — a length-prefixed binary frame format (``KF`` magic,
  versioned) for observation batches: struct-packed header, compact row
  encoding with IEEE-754 timestamps shipped bit-exactly (``!d`` — the
  truncate-to-checkpoint recovery rule compares these floats, so the wire
  must never round them). Truncated/torn/oversized frames are rejected
  loudly (:class:`FrameError`), never half-applied.
- **server** — :class:`IngestServer`: a ``selectors``-based (stdlib,
  zero-dependency) event loop serving persistent connections on a sibling
  ingest port, so N trial processes streaming metrics cost N *sockets*,
  not N threads. Frames from many connections are **coalesced** into one
  ``store.report_many`` group commit per drain window; each entry keeps
  the JSON receiver's idempotent exact-duplicate drop, so at-least-once
  delivery stays effectively-once across client reconnects. ACKs are sent
  only after the batch was handed to the store — the same durability
  point as the JSON path's 200.
- **client** — :class:`FramedIngestClient` / :class:`FramedObservationStore`:
  one pooled persistent socket per store, capped-backoff reconnect (the
  HttpApiClient retry policy), and resend-on-reconnect of the unacked
  frame. Reads and the rare control RPCs stay on the JSON plane.

Everything is gated by ``runtime.ingest_framed``
(``KATIB_TPU_INGEST_FRAMED``): off (the default), no ingest server is
constructed, no env is exported, and the wire is byte-identical to the
PR 15 JSON path (asserted by tests/test_ingest_plane.py's seeded
on-vs-off sweep).
"""

from __future__ import annotations

import logging
import math
import os
import selectors
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..db.store import MetricLog, ObservationStore

log = logging.getLogger("katib_tpu.ingest")

# env binding exported by a replica running with framed ingest on: trial
# subprocesses stream observation batches here instead of POSTing JSON
# (runtime/metrics.py resolves precedence: ingest > rpc url > db path)
ENV_INGEST_ADDR = "KATIB_TPU_INGEST_ADDR"

# -- frame format ------------------------------------------------------------
#
#   header (8 bytes):  !2sBBI  = magic "KF", version, frame type, payload len
#   HELLO   payload:   token utf-8 (may be empty)
#   DATA    payload:   !QI seq, n_entries, then per entry:
#                        !HI trial_len, n_rows + trial utf-8
#                        per row: !dHH timestamp, name_len, value_len
#                                 + name utf-8 + value utf-8
#   ACK     payload:   !Q  cumulative seq: every frame <= seq is in the store
#   ERR     payload:   !B  code + message utf-8
#                      code 1 = auth rejected   (client must not retry)
#                      code 2 = malformed frame (client must not retry)
#                      code 3 = store write failed (client reconnects+resends)
#   TDATA   payload:   !H traceparent_len + traceparent utf-8, then the DATA
#                      payload verbatim — the traced variant of DATA
#                      (runtime.wire_tracing, ISSUE 19). Clients with the
#                      knob off never emit TDATA, so the knob-off wire stays
#                      byte-identical to the untraced protocol; servers
#                      always accept both. A traceparent whose length field
#                      overruns the payload is a torn frame (ERR code 2);
#                      one that is in-bounds but content-invalid (regex
#                      fail, oversized) is warned about and IGNORED — the
#                      batch still lands, trace context is best-effort.
#
# The magic is versioned so JSON and framed clients can interoperate on one
# port if a future revision multiplexes them: a JSON POST starts "PO", never
# "KF", so the first two bytes of a connection identify the protocol.

MAGIC = b"KF"
VERSION = 1
F_HELLO, F_DATA, F_ACK, F_ERR, F_TDATA = 1, 2, 3, 4, 5
ERR_AUTH, ERR_FRAME, ERR_WRITE = 1, 2, 3

_HEADER = struct.Struct("!2sBBI")
_DATA_HEAD = struct.Struct("!QI")
_ENTRY_HEAD = struct.Struct("!HI")
_ROW_HEAD = struct.Struct("!dHH")
_SEQ = struct.Struct("!Q")
_TP_HEAD = struct.Struct("!H")

MAX_FRAME_BYTES = 8 * 1024 * 1024  # one group-commit batch, bounded


class FrameError(ValueError):
    """A torn, truncated, oversized or non-protocol frame. Always loud:
    the receiver closes the connection rather than guessing at row
    boundaries — the client's unacked frame is resent on reconnect."""


def _frame(ftype: int, payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(
            f"frame payload {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte bound — split the batch"
        )
    return _HEADER.pack(MAGIC, VERSION, ftype, len(payload)) + payload


def encode_hello(token: str = "") -> bytes:
    return _frame(F_HELLO, token.encode("utf-8"))


def encode_ack(seq: int) -> bytes:
    return _frame(F_ACK, _SEQ.pack(seq))


def encode_err(code: int, message: str) -> bytes:
    return _frame(F_ERR, bytes([code]) + message.encode("utf-8", "replace"))


def encode_data_frame(
    entries: Sequence[Tuple[str, Sequence[MetricLog]]],
    seq: int,
    traceparent: Optional[str] = None,
) -> bytes:
    """One observation batch -> one DATA frame. Timestamps travel as raw
    IEEE-754 doubles (bit-exact, NaN payloads and -0.0 included). With a
    ``traceparent`` the frame travels as TDATA — trace context prefixed,
    rows encoded identically; without one the bytes are exactly the
    untraced protocol's (the wire_tracing-off byte-identity contract)."""
    parts = [_DATA_HEAD.pack(seq, len(entries))]
    for trial_name, logs in entries:
        t = trial_name.encode("utf-8")
        if len(t) > 0xFFFF:
            raise FrameError(f"trial name {trial_name[:40]!r}... too long")
        parts.append(_ENTRY_HEAD.pack(len(t), len(logs)))
        parts.append(t)
        for row in logs:
            n = row.metric_name.encode("utf-8")
            v = row.value.encode("utf-8")
            if len(n) > 0xFFFF or len(v) > 0xFFFF:
                raise FrameError(
                    f"metric name/value too long in trial {trial_name!r}"
                )
            parts.append(_ROW_HEAD.pack(row.timestamp, len(n), len(v)))
            parts.append(n)
            parts.append(v)
    if traceparent is None:
        return _frame(F_DATA, b"".join(parts))
    tp = traceparent.encode("utf-8")
    if len(tp) > 0xFFFF:
        raise FrameError(f"traceparent too long ({len(tp)} bytes)")
    return _frame(F_TDATA, _TP_HEAD.pack(len(tp)) + tp + b"".join(parts))


def decode_tdata_payload(payload: bytes) -> Tuple[str, bytes]:
    """Split a TDATA payload into (traceparent, data_payload). Only the
    length prefix is validated here — an overrunning prefix is a torn frame
    (:class:`FrameError`); whether the traceparent CONTENT is a usable
    trace context is the receiver's call (warn + ignore, never reject)."""
    if len(payload) < _TP_HEAD.size:
        raise FrameError("torn tdata frame: missing traceparent length")
    (tp_len,) = _TP_HEAD.unpack_from(payload, 0)
    if _TP_HEAD.size + tp_len > len(payload):
        raise FrameError(
            f"torn tdata frame: traceparent length {tp_len} overruns the "
            f"{len(payload)}-byte payload"
        )
    tp = str(payload[_TP_HEAD.size:_TP_HEAD.size + tp_len], "utf-8", "replace")
    return tp, payload[_TP_HEAD.size + tp_len:]


def decode_data_payload(
    payload: bytes,
) -> Tuple[int, List[Tuple[str, List[MetricLog]]]]:
    """Strict inverse of :func:`encode_data_frame`; any overrun or leftover
    bytes raises :class:`FrameError` (a torn frame must never land rows)."""
    view = memoryview(payload)
    off = 0

    def take(n: int) -> memoryview:
        nonlocal off
        if off + n > len(view):
            raise FrameError(
                f"torn data frame: needed {n} bytes at offset {off}, "
                f"payload is {len(view)} bytes"
            )
        chunk = view[off:off + n]
        off += n
        return chunk

    seq, n_entries = _DATA_HEAD.unpack(take(_DATA_HEAD.size))
    entries: List[Tuple[str, List[MetricLog]]] = []
    for _ in range(n_entries):
        t_len, n_rows = _ENTRY_HEAD.unpack(take(_ENTRY_HEAD.size))
        trial_name = str(take(t_len), "utf-8")
        rows: List[MetricLog] = []
        for _ in range(n_rows):
            ts, n_len, v_len = _ROW_HEAD.unpack(take(_ROW_HEAD.size))
            name = str(take(n_len), "utf-8")
            value = str(take(v_len), "utf-8")
            rows.append(MetricLog(timestamp=ts, metric_name=name, value=value))
        entries.append((trial_name, rows))
    if off != len(view):
        raise FrameError(
            f"torn data frame: {len(view) - off} trailing bytes after "
            f"{n_entries} entries"
        )
    return seq, entries


def frames_from_buffer(buf: bytearray):
    """Yield complete ``(ftype, payload)`` frames from ``buf``, consuming
    them. Stops at an incomplete tail (more bytes pending); raises
    :class:`FrameError` on a non-protocol or oversized header."""
    while len(buf) >= _HEADER.size:
        magic, version, ftype, length = _HEADER.unpack_from(buf, 0)
        if magic != MAGIC:
            raise FrameError(f"bad magic {bytes(magic)!r} (not a KF frame)")
        if version != VERSION:
            raise FrameError(f"unsupported frame version {version}")
        if length > MAX_FRAME_BYTES:
            raise FrameError(
                f"declared payload {length} bytes exceeds the "
                f"{MAX_FRAME_BYTES}-byte bound"
            )
        if len(buf) < _HEADER.size + length:
            return  # incomplete: wait for more bytes
        payload = bytes(buf[_HEADER.size:_HEADER.size + length])
        del buf[:_HEADER.size + length]
        yield ftype, payload


# -- server ------------------------------------------------------------------


class _Conn:
    __slots__ = ("sock", "rbuf", "wbuf", "authed", "peer", "closing", "ident")

    def __init__(self, sock: socket.socket, peer) -> None:
        self.sock = sock
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.authed = False
        self.peer = peer
        self.closing = False  # flush wbuf, then close
        self.ident = None     # tenancy mode: Identity resolved at HELLO


class IngestServer:
    """Event-loop listener for framed observation streaming.

    One thread runs the ``selectors`` loop: accepts persistent
    connections, parses frames, and coalesces DATA frames from MANY
    connections into one ``store.report_many`` group commit per drain.
    The drain fires when the coalesce window elapses, the pending batch
    reaches ``coalesce_rows``, or the loop goes quiescent (no more
    readable sockets — every sync client is waiting on its ACK, so
    waiting out the window would only add latency).

    Delivery contract (mirrors the JSON ``ReportManyObservationLogs``
    receiver): per-entry idempotent exact-duplicate drop against the
    store, ACK only after ``report_many`` returned — a client that never
    saw the ACK resends the identical frame and the dedup makes it a
    no-op. A store write failure ERRs (code 3) every contributing
    connection instead of acking, so no row is silently dropped.
    """

    def __init__(
        self,
        store: ObservationStore,
        host: str = "127.0.0.1",
        port: int = 0,
        auth_token: Optional[str] = None,
        metrics=None,
        coalesce_window_s: float = 0.005,
        coalesce_rows: int = 4096,
        tenants=None,
        tracer=None,
        events=None,
    ) -> None:
        self.store = store
        self.auth_token = auth_token
        self.tenants = tenants  # TenantRegistry; None = tenancy off
        self.metrics = metrics
        # distributed tracing plane (ISSUE 19): a replica running with
        # runtime.wire_tracing passes its controller Tracer here — TDATA
        # frames then land the same `rpc.report_observation_log` span the
        # JSON receiver records, plus one `ingest.group_commit` span per
        # contributing trace per drain. No tracer (the default) = the
        # PR 16 span set, which is what knob-off byte-identity asserts.
        self.tracer = tracer
        self.events = events
        self.coalesce_window_s = max(0.0, float(coalesce_window_s))
        self.coalesce_rows = max(1, int(coalesce_rows))
        self._lsock = socket.create_server((host, port))
        self._lsock.setblocking(False)
        self.bound_port = self._lsock.getsockname()[1]
        self.host = host
        self.address = f"{host}:{self.bound_port}"
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        # self-pipe: close() wakes the loop out of select immediately
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        # (conn, seq, entries, n_rows, trace_ctx) — trace_ctx is the parsed
        # (trace_id, parent_span_id) of a TDATA frame, None for plain DATA
        self._pending: List[
            Tuple[_Conn, int, List[Tuple[str, List[MetricLog]]], int, Optional[Tuple[str, str]]]
        ] = []
        self._pending_rows = 0
        self._pending_since: Optional[float] = None
        self._closed = False
        self.stats = {"frames_total": 0, "drains_total": 0, "rows_total": 0}
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="katib-ingest-loop"
        )
        self._thread.start()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._thread.join(timeout=5.0)
        try:
            self._wake_w.close()
        except OSError:
            pass

    # -- event loop ----------------------------------------------------------

    def _loop(self) -> None:
        try:
            while not self._closed:
                if self._pending_since is not None:
                    elapsed = time.monotonic() - self._pending_since
                    timeout = max(0.0, self.coalesce_window_s - elapsed)
                else:
                    timeout = 0.5
                events = self._sel.select(timeout)
                if self._closed:
                    break
                for key, mask in events:
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        try:
                            self._wake_r.recv(64)
                        except OSError:
                            pass
                    else:
                        conn = key.data
                        if mask & selectors.EVENT_READ:
                            self._readable(conn)
                        if mask & selectors.EVENT_WRITE:
                            self._writable(conn)
                if self._pending and (
                    self._pending_rows >= self.coalesce_rows
                    or time.monotonic() - self._pending_since >= self.coalesce_window_s
                    or not self._sel.select(0)  # quiescent: every client is
                    # blocked on its ACK; draining now costs nothing
                ):
                    self._drain()
        finally:
            for key in list(self._sel.get_map().values()):
                if isinstance(key.data, _Conn):
                    self._close_conn(key.data)
            self._sel.unregister(self._lsock)
            self._lsock.close()
            try:
                self._sel.unregister(self._wake_r)
            except KeyError:
                pass
            self._wake_r.close()
            self._sel.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, peer = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, peer)
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _interest(self, conn: _Conn) -> None:
        mask = selectors.EVENT_READ
        if conn.wbuf:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, conn)
        except (KeyError, ValueError):
            pass

    def _send(self, conn: _Conn, data: bytes) -> None:
        conn.wbuf += data
        self._writable(conn)

    def _writable(self, conn: _Conn) -> None:
        try:
            while conn.wbuf:
                sent = conn.sock.send(conn.wbuf)
                if sent <= 0:
                    break
                del conn.wbuf[:sent]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close_conn(conn)
            return
        if conn.closing and not conn.wbuf:
            self._close_conn(conn)
            return
        self._interest(conn)

    def _close_conn(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        # a partially-read frame dies with the connection; the client never
        # saw an ACK for it and resends on reconnect (dedup absorbs overlap)
        self._pending = [p for p in self._pending if p[0] is not conn]
        self._pending_rows = sum(p[3] for p in self._pending)
        if not self._pending:
            self._pending_since = None

    def _readable(self, conn: _Conn) -> None:
        try:
            while True:
                chunk = conn.sock.recv(262144)
                if not chunk:
                    self._close_conn(conn)
                    return
                conn.rbuf += chunk
                if len(chunk) < 262144:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close_conn(conn)
            return
        try:
            for ftype, payload in frames_from_buffer(conn.rbuf):
                self._frame(conn, ftype, payload)
        except FrameError as e:
            log.warning("ingest: rejecting %s from %s", e, conn.peer)
            conn.closing = True
            self._send(conn, encode_err(ERR_FRAME, str(e)))

    def _deny_tenant(self, tenant: Optional[str]) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                "katib_tenant_denied_total",
                tenant=tenant or "(unresolved)", plane="framed",
            )

    def _trace_ctx(self, conn: _Conn, tp: str) -> Optional[Tuple[str, str]]:
        """Validate a TDATA traceparent. Invalid content is dropped LOUDLY
        (warning event) but never rejects the frame — observability context
        must not cost data."""
        from ..tracing import MAX_TRACEPARENT_LEN, parse_traceparent

        if not tp:
            return None
        if len(tp) > MAX_TRACEPARENT_LEN:
            self._trace_warn(conn, f"oversized traceparent ({len(tp)} chars)")
            return None
        ctx = parse_traceparent(tp)
        if ctx is None:
            self._trace_warn(conn, f"malformed traceparent {tp[:48]!r}")
            return None
        return ctx

    def _trace_warn(self, conn: _Conn, why: str) -> None:
        log.warning("ingest: ignoring %s from %s", why, conn.peer)
        if self.events is not None:
            try:
                self.events.event(
                    "_wire", "Ingest", str(conn.peer), "TraceContextInvalid",
                    f"ignoring {why}; frame still served", warning=True,
                )
            except Exception:
                pass  # event plumbing must never unwind the ingest loop

    def _frame(self, conn: _Conn, ftype: int, payload: bytes) -> None:
        if ftype == F_HELLO:
            if self.tenants is not None:
                # tenancy mode (service/tenancy.py): the HELLO token resolves
                # to an identity whose namespace every DATA entry must honor
                from .tenancy import resolve_wire_identity

                ident = resolve_wire_identity(
                    self.tenants, self.auth_token, str(payload, "utf-8", "replace")
                )
                if ident is None:
                    self._deny_tenant(None)
                    conn.closing = True
                    self._send(
                        conn, encode_err(ERR_AUTH, "missing or invalid auth token")
                    )
                    return
                conn.ident = ident
            elif self.auth_token is not None:
                import secrets

                if not secrets.compare_digest(payload, self.auth_token.encode()):
                    conn.closing = True
                    self._send(
                        conn, encode_err(ERR_AUTH, "missing or invalid auth token")
                    )
                    return
            conn.authed = True
            self._send(conn, encode_ack(0))
            return
        if ftype in (F_DATA, F_TDATA):
            if self.auth_token is not None and not conn.authed:
                conn.closing = True
                self._send(conn, encode_err(ERR_AUTH, "HELLO with token required"))
                return
            if self.tenants is not None and conn.ident is None:
                # no HELLO yet: resolve as an anonymous peer (break-glass
                # only when no global token is configured)
                from .tenancy import resolve_wire_identity

                conn.ident = resolve_wire_identity(self.tenants, self.auth_token, "")
                if conn.ident is None:
                    self._deny_tenant(None)
                    conn.closing = True
                    self._send(conn, encode_err(ERR_AUTH, "HELLO with token required"))
                    return
            ctx: Optional[Tuple[str, str]] = None
            if ftype == F_TDATA:
                # structural damage (overrunning length prefix) raises
                # FrameError into the caller's reject path; content-invalid
                # trace context is warned about and dropped, the rows land
                tp, payload = decode_tdata_payload(payload)
                ctx = self._trace_ctx(conn, tp)
            seq, entries = decode_data_payload(payload)
            if conn.ident is not None and conn.ident.tenant is not None:
                for trial_name, _rows in entries:
                    if not conn.ident.owns(trial_name):
                        self._deny_tenant(conn.ident.tenant)
                        conn.closing = True
                        self._send(
                            conn,
                            encode_err(
                                ERR_AUTH,
                                f"tenant {conn.ident.tenant!r} does not own "
                                f"{trial_name!r}",
                            ),
                        )
                        return
            n_rows = sum(len(rows) for _, rows in entries)
            self._pending.append((conn, seq, entries, n_rows, ctx))
            self._pending_rows += n_rows
            if self._pending_since is None:
                self._pending_since = time.monotonic()
            self.stats["frames_total"] += 1
            if self.metrics is not None:
                self.metrics.inc("katib_ingest_frames_total")
            return
        raise FrameError(f"unexpected frame type {ftype} from a client")

    # -- coalesced drain -----------------------------------------------------

    def _drain(self) -> None:
        batch, self._pending = self._pending, []
        rows_in = self._pending_rows
        self._pending_rows = 0
        self._pending_since = None
        t0 = time.time()
        # merge all frames' entries per trial, preserving arrival order
        by_trial: Dict[str, List[MetricLog]] = {}
        for _, _, entries, _, _ in batch:
            for trial_name, rows in entries:
                by_trial.setdefault(trial_name, []).extend(rows)
        fresh_entries: List[Tuple[str, List[MetricLog]]] = []
        err: Optional[BaseException] = None
        try:
            for trial_name, rows in by_trial.items():
                fresh = self._dedup(trial_name, rows)
                if fresh:
                    fresh_entries.append((trial_name, fresh))
            if fresh_entries:
                self.store.report_many(fresh_entries)
        except BaseException as e:  # surface to every contributor, stay up
            err = e
            log.error("ingest: coalesced group commit failed: %s", e)
        # stats/metrics BEFORE the acks go out: a client acts on its ACK
        # immediately (scrapes /metrics, asserts in tests) and must observe
        # this drain already counted
        if err is None:
            self.stats["drains_total"] += 1
            self.stats["rows_total"] += rows_in
            if self.metrics is not None:
                self.metrics.inc("katib_ingest_batch_rows", value=float(rows_in))
                self.metrics.set_gauge(
                    "katib_ingest_coalesce_depth", float(len(batch))
                )
            self._record_drain_spans(batch, rows_in, t0)
        acks: Dict[_Conn, int] = {}
        for conn, seq, _, _, _ in batch:
            acks[conn] = max(acks.get(conn, 0), seq)
        for conn, seq in acks.items():
            if err is not None:
                conn.closing = True
                self._send(conn, encode_err(ERR_WRITE, f"store write failed: {err}"))
            else:
                self._send(conn, encode_ack(seq))

    def _record_drain_spans(self, batch, rows_in: int, t0: float) -> None:
        """Span parity with the JSON wire (ISSUE 19): every traced frame's
        entries land a ``rpc.report_observation_log`` span in the caller's
        trace (the exact span the JSON servicer records), and each
        contributing trace gets one ``ingest.group_commit`` span for this
        drain — all sharing a ``commitId`` attr plus the sibling trace ids,
        so a merged tree shows which trials' writes were coalesced."""
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            return
        end = time.time()
        ctxs: Dict[str, Optional[str]] = {}  # trace_id -> parent span id
        for _, _, entries, _, ctx in batch:
            if ctx is None:
                continue
            trace_id, parent_id = ctx
            ctxs.setdefault(trace_id, parent_id)
            for trial_name, rows in entries:
                tracer.record_span(
                    "rpc.report_observation_log", "_rpc", trace_id, parent_id,
                    start=t0, end=end, trial=trial_name, rows=len(rows),
                )
        if not ctxs:
            return
        commit_id = tracer.new_span_id()
        linked = sorted(ctxs)
        for trace_id, parent_id in ctxs.items():
            tracer.record_span(
                "ingest.group_commit", "_rpc", trace_id, parent_id,
                start=t0, end=end, commitId=commit_id,
                frames=len(batch), rows=rows_in,
                linkedTraces=[t for t in linked if t != trace_id],
            )

    def _dedup(self, trial_name: str, rows: List[MetricLog]) -> List[MetricLog]:
        """The JSON receiver's idempotent exact-duplicate drop, batched: one
        windowed store read per trial per drain (instead of per entry), plus
        intra-batch dedup so a resent frame coalescing with its original
        never lands twice."""
        min_ts = min(
            (r.timestamp for r in rows if not math.isnan(r.timestamp)),
            default=None,
        )
        seen = set()
        if min_ts is not None:
            seen = {
                (r.timestamp, r.metric_name, r.value)
                for r in self.store.get_observation_log(trial_name, start_time=min_ts)
            }
        fresh: List[MetricLog] = []
        for r in rows:
            key = (r.timestamp, r.metric_name, r.value)
            if key in seen:
                continue
            seen.add(key)
            fresh.append(r)
        return fresh


# -- client ------------------------------------------------------------------

# shared retry policy with the JSON client (service/httpapi.py)
from .httpapi import (  # noqa: E402  (import placed after codec: no cycle —
    DEFAULT_BACKOFF_BASE_S,  # httpapi never imports this module)
    DEFAULT_BACKOFF_CAP_S,
    DEFAULT_HTTP_RETRIES,
    HttpRemoteObservationStore,
    RpcError,
)


class FramedIngestClient:
    """One persistent framed connection to an :class:`IngestServer`.

    ``report_many`` is synchronous at-least-once: encode one DATA frame,
    send, wait for the cumulative ACK. Connection failures and ERR-code-3
    (store write failed) reconnect with the capped exponential backoff of
    the JSON client and RESEND the identical frame — the server's
    exact-duplicate drop makes the retry effectively-once. Auth and
    protocol rejections raise :class:`RpcError` immediately (the 4xx
    rule: never retried into duplicates)."""

    def __init__(
        self,
        address: str,
        token: Optional[str] = None,
        timeout: float = 30.0,
        retries: int = DEFAULT_HTTP_RETRIES,
        backoff_base: float = DEFAULT_BACKOFF_BASE_S,
        backoff_cap: float = DEFAULT_BACKOFF_CAP_S,
        wire_tracing: Optional[bool] = None,
    ) -> None:
        host, _, port = address.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"ingest address must be host:port, got {address!r}")
        self.address = address
        self.host = host
        self.port = int(port)
        self.token = token
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        # wire_tracing on -> DATA frames travel as TDATA with the current
        # traceparent; off (default) -> byte-identical untraced frames.
        # None resolves from $KATIB_TPU_WIRE_TRACING, the only knob a trial
        # subprocess has (no RuntimeConfig handle down here).
        if wire_tracing is None:
            from ..tracing import wire_tracing_from_env

            wire_tracing = wire_tracing_from_env()
        self.wire_tracing = wire_tracing
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rbuf = bytearray()
        self._seq = 0

    # -- connection management ----------------------------------------------

    def _connect_locked(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._sock = sock
        self._rbuf.clear()
        sock.sendall(encode_hello(self.token or ""))
        self._await_ack_locked(0)

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._rbuf.clear()

    def _await_ack_locked(self, target_seq: int) -> None:
        deadline = time.monotonic() + self.timeout
        while True:
            for ftype, payload in frames_from_buffer(self._rbuf):
                if ftype == F_ACK:
                    (seq,) = _SEQ.unpack(payload)
                    if seq >= target_seq:
                        return
                elif ftype == F_ERR:
                    code = payload[0] if payload else 0
                    message = str(payload[1:], "utf-8", "replace")
                    self._close_locked()
                    if code == ERR_WRITE:
                        # transient: the reconnect loop resends the frame
                        raise ConnectionError(f"ingest server: {message}")
                    raise RpcError(
                        f"ingest {self.address} rejected: {message}",
                        code=403 if code == ERR_AUTH else 400,
                    )
                else:
                    raise FrameError(f"unexpected frame type {ftype} from server")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no ACK from {self.address} within {self.timeout}s"
                )
            self._sock.settimeout(remaining)
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError(f"ingest {self.address} closed mid-ack")
            self._rbuf += chunk

    # -- the hot path --------------------------------------------------------

    def report_many(self, entries: Sequence[Tuple[str, Sequence[MetricLog]]]) -> None:
        batch = [(t, list(ls)) for t, ls in entries if ls]
        if not batch:
            return
        tp = None
        if self.wire_tracing:
            from ..tracing import current_traceparent

            tp = current_traceparent()
        with self._lock:
            self._seq += 1
            frame = encode_data_frame(batch, self._seq, traceparent=tp)
            last: Optional[BaseException] = None
            for attempt in range(self.retries):
                try:
                    if self._sock is None:
                        self._connect_locked()
                    self._sock.settimeout(self.timeout)
                    self._sock.sendall(frame)
                    self._await_ack_locked(self._seq)
                    return
                except RpcError:
                    raise  # auth/protocol rejection: the 4xx rule
                except (OSError, FrameError, TimeoutError, ConnectionError) as e:
                    last = e
                    self._close_locked()
                if attempt < self.retries - 1:
                    time.sleep(
                        min(self.backoff_base * (2 ** attempt), self.backoff_cap)
                    )
            raise RpcError(
                f"framed ingest to {self.address} failed after "
                f"{self.retries} attempt(s): {last}"
            ) from last

    def close(self) -> None:
        with self._lock:
            self._close_locked()


class FramedObservationStore(ObservationStore):
    """ObservationStore whose WRITE path is the framed ingest plane and
    whose read/control path stays on the JSON wire — what a trial process
    under ``KATIB_TPU_INGEST_ADDR`` uses. ``report_many`` ships a whole
    group-commit batch as ONE binary frame over a persistent socket, so
    the buffered store's flusher pays neither connection setup nor a JSON
    codec per drained batch."""

    def __init__(
        self,
        ingest_addr: str,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        timeout: float = 30.0,
        retries: int = DEFAULT_HTTP_RETRIES,
        wire_tracing: Optional[bool] = None,
    ) -> None:
        self.ingest = FramedIngestClient(
            ingest_addr, token=token, timeout=timeout, retries=retries,
            wire_tracing=wire_tracing,
        )
        self._http: Optional[HttpRemoteObservationStore] = (
            HttpRemoteObservationStore(
                base_url, token=token, timeout=timeout, retries=retries
            )
            if base_url
            else None
        )

    def _control(self) -> HttpRemoteObservationStore:
        if self._http is None:
            raise RpcError(
                "framed store has no JSON control-plane url (base_url) — "
                "reads/truncate/delete need the rpc binding"
            )
        return self._http

    def report_observation_log(
        self, trial_name: str, logs: Sequence[MetricLog]
    ) -> None:
        self.ingest.report_many([(trial_name, logs)])

    def report_many(self, entries: Sequence[Tuple[str, Sequence[MetricLog]]]) -> None:
        self.ingest.report_many(entries)

    def get_observation_log(
        self, trial_name, metric_name=None, start_time=None, end_time=None, limit=None
    ):
        return self._control().get_observation_log(
            trial_name, metric_name=metric_name,
            start_time=start_time, end_time=end_time, limit=limit,
        )

    def folded(self, trial_name, metric_names):
        return self._control().folded(trial_name, metric_names)

    def truncate_observation_log(self, trial_name: str, after_time: float) -> int:
        return self._control().truncate_observation_log(trial_name, after_time)

    def delete_observation_log(self, trial_name: str) -> None:
        self._control().delete_observation_log(trial_name)

    def close(self) -> None:
        self.ingest.close()
