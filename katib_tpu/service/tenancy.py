"""Tenancy plane — identity, scoped tokens, namespaces, and quotas.

ISSUE 17 turns the single-credential control plane into a multi-tenant
service tier. The pieces, in dependency order:

- **TenantRegistry** — atomic JSON records under ``<root>/tenants/``,
  shared by every replica the same way the placement registry is
  (``controller/placement.py``): one file per tenant, written tmp +
  ``os.replace`` so a crash mid-write never corrupts a record. Each
  record mints one bearer token per scope (``admin``: every verb inside
  the tenant's namespace, including create/delete/truncate; ``writer``:
  report/read observation verbs only — the credential a trial process
  carries). The single global ``auth_token`` stays as a *break-glass*
  admin credential resolving to an unrestricted identity.

- **Namespaces** — tenant ``acme`` owns every experiment named
  ``acme--<rest>``. Tenant names are ``[a-z][a-z0-9]*`` (no dashes), so
  the ``--`` separator is unambiguous under the experiment-name grammar
  (``api/validation.py`` NAME_RE). Trial names derive from experiment
  names (``suggest/base.py``), so observation-log rows and the
  ``experiment_history`` warm-start index are namespaced transitively —
  ownership of any resource reduces to a prefix check on its name.

- **Quotas** — per-tenant admission rate (token bucket, refused with a
  tenant-tagged 429, never silently queued) and concurrency/device caps
  compiled down onto the existing engines: ``max_experiments`` is
  checked against the tenant's live placement claims (PR 15) and
  ``device_quota`` / ``fair_share_weight`` are stamped onto the spec so
  the PR 2 fair-share scheduler enforces them unchanged.

``KATIB_TPU_TENANCY`` unset keeps every wire path byte-identical to the
single-tenant plane: the registry is simply never constructed, and all
enforcement hangs off ``registry is None``.
"""

from __future__ import annotations

import hmac
import json
import logging
import os
import re
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

log = logging.getLogger("katib_tpu.tenancy")

ENV_TENANCY = "KATIB_TPU_TENANCY"

SCOPE_ADMIN = "admin"
SCOPE_WRITER = "writer"
SCOPES = (SCOPE_ADMIN, SCOPE_WRITER)

# scopes are ordered: admin may do everything writer may
_SCOPE_RANK = {SCOPE_WRITER: 0, SCOPE_ADMIN: 1}

SEP = "--"
# no dashes in tenant names — keeps "<tenant>--<experiment>" unambiguous
TENANT_RE = re.compile(r"^[a-z][a-z0-9]*$")

TENANTS_DIRNAME = "tenants"


def namespaced(tenant: str, name: str) -> str:
    """The canonical resource name for ``name`` inside ``tenant``."""
    return f"{tenant}{SEP}{name}"


def tenant_of(name: str) -> Optional[str]:
    """The owning tenant encoded in a resource name, or None for names
    outside any tenant namespace (single-tenant / pre-tenancy rows)."""
    head, sep, rest = name.partition(SEP)
    if not sep or not rest:
        return None
    return head if TENANT_RE.match(head) else None


@dataclass(frozen=True)
class Identity:
    """A resolved caller. ``tenant=None`` is the break-glass admin (the
    global ``auth_token``, or an open deployment with auth disabled)."""

    tenant: Optional[str]
    scope: str = SCOPE_ADMIN

    def owns(self, name: str) -> bool:
        if self.tenant is None:
            return True
        return tenant_of(name) == self.tenant

    def allows(self, scope: str) -> bool:
        return _SCOPE_RANK.get(self.scope, -1) >= _SCOPE_RANK.get(scope, 1)


BREAK_GLASS = Identity(tenant=None, scope=SCOPE_ADMIN)


@dataclass
class TenantRecord:
    """One tenant: scoped tokens plus its quota envelope. ``0`` /
    ``None`` quota fields mean unlimited."""

    name: str
    tokens: Dict[str, str] = field(default_factory=dict)  # scope -> token
    admission_per_minute: float = 0.0
    max_experiments: int = 0
    device_quota: Optional[int] = None
    fair_share_weight: float = 1.0
    shared_history: bool = False
    created_at: float = 0.0

    def to_doc(self) -> dict:
        doc = {
            "name": self.name,
            "tokens": dict(self.tokens),
            "quota": {
                "admissionPerMinute": self.admission_per_minute,
                "maxExperiments": self.max_experiments,
                "fairShareWeight": self.fair_share_weight,
            },
            "sharedHistory": self.shared_history,
            "createdAt": self.created_at,
        }
        if self.device_quota is not None:
            doc["quota"]["deviceQuota"] = self.device_quota
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "TenantRecord":
        quota = doc.get("quota") or {}
        return cls(
            name=doc["name"],
            tokens=dict(doc.get("tokens") or {}),
            admission_per_minute=float(quota.get("admissionPerMinute", 0.0)),
            max_experiments=int(quota.get("maxExperiments", 0)),
            device_quota=(
                int(quota["deviceQuota"]) if "deviceQuota" in quota else None
            ),
            fair_share_weight=float(quota.get("fairShareWeight", 1.0)),
            shared_history=bool(doc.get("sharedHistory", False)),
            created_at=float(doc.get("createdAt", 0.0)),
        )


class TenantRegistry:
    """Replica-shared tenant records under ``<root>/tenants/``.

    Reads are mtime-cached per file so the hot wire path (every RPC
    resolves a token) stays cheap; writes go through tmp + os.replace so
    concurrent replicas always see a whole record.
    """

    def __init__(self, root_dir: str):
        self.root_dir = root_dir
        self.dir = os.path.join(root_dir, TENANTS_DIRNAME)
        self._lock = threading.Lock()
        self._cache: Dict[str, tuple] = {}  # name -> (mtime, record)

    # -- persistence ---------------------------------------------------------

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, f"{name}.json")

    def save(self, rec: TenantRecord) -> TenantRecord:
        os.makedirs(self.dir, exist_ok=True)
        path = self._path(rec.name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec.to_doc(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        with self._lock:
            self._cache.pop(rec.name, None)
        return rec

    def create(
        self,
        name: str,
        *,
        admission_per_minute: float = 0.0,
        max_experiments: int = 0,
        device_quota: Optional[int] = None,
        fair_share_weight: float = 1.0,
        shared_history: bool = False,
    ) -> TenantRecord:
        if not TENANT_RE.match(name):
            raise ValueError(
                f"invalid tenant name {name!r}: must match {TENANT_RE.pattern}"
            )
        if os.path.exists(self._path(name)):
            raise ValueError(f"tenant {name!r} already exists")
        rec = TenantRecord(
            name=name,
            tokens={scope: secrets.token_hex(16) for scope in SCOPES},
            admission_per_minute=admission_per_minute,
            max_experiments=max_experiments,
            device_quota=device_quota,
            fair_share_weight=fair_share_weight,
            shared_history=shared_history,
            created_at=time.time(),
        )
        return self.save(rec)

    def load(self, name: str) -> Optional[TenantRecord]:
        path = self._path(name)
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            return None
        with self._lock:
            hit = self._cache.get(name)
            if hit is not None and hit[0] == mtime:
                return hit[1]
        try:
            with open(path, "r", encoding="utf-8") as f:
                rec = TenantRecord.from_doc(json.load(f))
        except (OSError, ValueError, KeyError):
            log.warning("unreadable tenant record %s", path, exc_info=True)
            return None
        with self._lock:
            self._cache[name] = (mtime, rec)
        return rec

    def delete(self, name: str) -> bool:
        with self._lock:
            self._cache.pop(name, None)
        try:
            os.remove(self._path(name))
            return True
        except OSError:
            return False

    def names(self) -> List[str]:
        try:
            entries = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(
            e[: -len(".json")] for e in entries if e.endswith(".json")
        )

    def records(self) -> List[TenantRecord]:
        return [r for r in (self.load(n) for n in self.names()) if r is not None]

    # -- identity ------------------------------------------------------------

    def resolve(self, token: str) -> Optional[Identity]:
        """Map a presented bearer token to a tenant identity. Constant-time
        comparison per token; the registry is small (one file per tenant),
        and reads are mtime-cached."""
        if not token:
            return None
        for rec in self.records():
            for scope, minted in rec.tokens.items():
                if minted and hmac.compare_digest(token, minted):
                    if scope not in _SCOPE_RANK:
                        continue
                    return Identity(tenant=rec.name, scope=scope)
        return None


def resolve_wire_identity(
    registry: Optional[TenantRegistry],
    auth_token: Optional[str],
    presented: Optional[str],
) -> Optional[Identity]:
    """Shared identity resolution for both wire planes (httpapi JSON and
    the framed ingest HELLO) when tenancy is on.

    - global ``auth_token`` match -> break-glass admin
    - tenant token match -> that tenant's identity at the token's scope
    - no token presented and no global token configured -> break-glass
      (an open deployment is already fully open; the ``AuthDisabled``
      startup event makes that visible)
    - anything else -> None (reject)
    """
    if presented:
        if auth_token and hmac.compare_digest(presented, auth_token):
            return BREAK_GLASS
        if registry is not None:
            return registry.resolve(presented)
        return None
    if auth_token:
        return None
    return BREAK_GLASS


class AdmissionLimiter:
    """Per-tenant token bucket over ``admission_per_minute``. Burst is a
    sixth of the per-minute rate (>= 1) so a tenant can land a small
    batch instantly but cannot front-load its whole minute.

    With ``shared_dir`` set (the tenants directory) the bucket state
    lives in one flock-serialized file per tenant, so N replicas share
    ONE budget — a client whose create was refused on replica A cannot
    launder the refusal by retrying against replica B. Without it the
    bucket is in-process (unit tests, single-replica controllers)."""

    def __init__(self, shared_dir: Optional[str] = None, clock=time.monotonic):
        self._dir = shared_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, List[float]] = {}  # tenant -> [tokens, at]

    @staticmethod
    def _refill(tokens: float, at: float, now: float, per_minute: float):
        rate = per_minute / 60.0
        burst = max(1.0, per_minute / 6.0)
        return min(burst, tokens + (now - at) * rate)

    def allow(self, tenant: str, per_minute: float) -> bool:
        if per_minute <= 0:
            return True
        if self._dir is not None:
            return self._allow_shared(tenant, per_minute)
        now = self._clock()
        burst = max(1.0, per_minute / 6.0)
        with self._lock:
            tokens, at = self._buckets.get(tenant, (burst, now))
            tokens = self._refill(tokens, at, now, per_minute)
            if tokens < 1.0:
                self._buckets[tenant] = [tokens, now]
                return False
            self._buckets[tenant] = [tokens - 1.0, now]
            return True

    def _allow_shared(self, tenant: str, per_minute: float) -> bool:
        import fcntl

        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(self._dir, f"{tenant}.bucket")
        burst = max(1.0, per_minute / 6.0)
        # wall clock, not monotonic: the bucket is shared across processes
        now = time.time()
        with open(path, "a+", encoding="utf-8") as f:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            f.seek(0)
            try:
                doc = json.loads(f.read() or "{}")
            except ValueError:
                doc = {}  # torn write: reset — a quota bucket, not a ledger
            tokens = self._refill(
                float(doc.get("tokens", burst)),
                float(doc.get("at", now)),
                now,
                per_minute,
            )
            ok = tokens >= 1.0
            if ok:
                tokens -= 1.0
            f.seek(0)
            f.truncate()
            f.write(json.dumps({"tokens": tokens, "at": now}))
            return ok


def claimed_experiments(root_dir: str, tenant: str) -> List[str]:
    """The tenant's experiments currently holding a placement lease —
    the PR 15 claim surface its ``max_experiments`` quota counts
    against. Completed experiments release their slot."""
    from ..controller import placement

    try:
        table = placement.placement_table(root_dir)
    except Exception:
        return []
    out: List[str] = []
    for lease in table.get("leases", []):
        name = lease.get("experiment", "")
        if tenant_of(name) != tenant:
            continue
        if lease.get("completed"):
            continue
        out.append(name)
    return sorted(out)


def scoped_history_signature(
    registry: Optional[TenantRegistry], experiment_name: str, signature: str
) -> str:
    """Tenant-scope a warm-start signature (``controller/suggestion.py``).

    With tenancy off (no registry) or for un-namespaced experiments the
    signature passes through untouched — byte-identical single-tenant
    behavior. A namespaced experiment reads/writes a tenant-prefixed
    signature, so ``matching_history`` can never return another tenant's
    rows; a tenant with ``shared_history`` opts into the global pool by
    keeping the plain signature.
    """
    if registry is None:
        return signature
    tenant = tenant_of(experiment_name)
    if tenant is None:
        return signature
    rec = registry.load(tenant)
    if rec is not None and rec.shared_history:
        return signature
    return f"tenant:{tenant}:{signature}"
