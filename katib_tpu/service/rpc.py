"""gRPC plane — out-of-process suggestion / early-stopping / DB-manager
services.

reference pkg/apis/manager/v1beta1/api.proto: services ``Suggestion``
(GetSuggestions, ValidateAlgorithmSettings), ``EarlyStopping``
(GetEarlyStoppingRules, SetTrialStatus, ValidateEarlyStoppingSettings) and
``DBManager`` (ReportObservationLog, GetObservationLog,
DeleteObservationLog), each served on port 6789 with a gRPC health service
(cmd/suggestion/*/main.py:26-42, cmd/db-manager/main.go).

The in-process engine (katib_tpu.suggest.base.Suggester, earlystop,
db.store) is the primary path; this module exposes the SAME contracts over
gRPC so algorithm services can run as separate processes/pods exactly like
the reference's per-experiment deployments. Messages are the dataclasses'
JSON encodings over a generic bytes codec (no protoc codegen dependency —
grpc_python_plugin is not available in this image; the method surface and
semantics mirror api.proto one-to-one and are documented per handler).
"""

from __future__ import annotations

import json
import threading
from concurrent import futures
from typing import Any, Callable, Dict, List, Optional, Sequence

import grpc

from ..api.spec import EarlyStoppingRule, ExperimentSpec, TrialAssignment
from ..api.status import Trial, TrialCondition
from ..db.store import MetricLog, ObservationStore
from ..earlystop.medianstop import EarlyStopper, create_early_stopper
from ..suggest.base import Suggester, SuggestionRequest, create

DEFAULT_PORT = 6789
SERVICE = "katib.tpu.v1.Api"

_ident = lambda b: b


def _json_bytes(obj: Any) -> bytes:
    return json.dumps(obj).encode()


def _request(experiment: ExperimentSpec, trials: Sequence[Trial], current: int, total: int) -> Dict:
    return {
        "experiment": experiment.to_dict(),
        "trials": [t.to_dict() for t in trials],
        "currentRequestNumber": current,
        "totalRequestNumber": total,
    }


class ApiServicer:
    """All three api.proto services behind one JSON-bytes gRPC service."""

    def __init__(
        self,
        suggester_factory: Callable[[str], Suggester] = create,
        store: Optional[ObservationStore] = None,
    ):
        self._suggester_factory = suggester_factory
        self._suggesters: Dict[str, Suggester] = {}
        self._early_stoppers: Dict[str, EarlyStopper] = {}
        self._lock = threading.Lock()
        self.store = store
        self.trial_status_overrides: Dict[str, str] = {}

    def _suggester(self, algo: str, experiment_name: str) -> Suggester:
        key = f"{experiment_name}/{algo}"
        with self._lock:
            if key not in self._suggesters:
                self._suggesters[key] = self._suggester_factory(algo)
            return self._suggesters[key]

    # -- Suggestion service (api.proto:36-43) -------------------------------

    def get_suggestions(self, payload: Dict) -> Dict:
        spec = ExperimentSpec.from_dict(payload["experiment"])
        trials = [Trial.from_dict(t) for t in payload.get("trials", [])]
        req = SuggestionRequest(
            experiment=spec,
            trials=trials,
            current_request_number=int(payload.get("currentRequestNumber", 0)),
            total_request_number=int(payload.get("totalRequestNumber", 0)),
        )
        reply = self._suggester(spec.algorithm.algorithm_name, spec.name).get_suggestions(req)
        return {
            "assignments": [a.to_dict() for a in reply.assignments],
            "algorithmSettings": reply.algorithm_settings,
            "searchEnded": reply.search_ended,
        }

    def validate_algorithm_settings(self, payload: Dict) -> Dict:
        spec = ExperimentSpec.from_dict(payload["experiment"])
        self._suggester(spec.algorithm.algorithm_name, spec.name).validate_algorithm_settings(spec)
        return {}

    # -- EarlyStopping service (api.proto:45-48) -----------------------------

    def _early_stopper(self, algo: str, experiment_name: str) -> EarlyStopper:
        key = f"{experiment_name}/{algo}"
        with self._lock:
            if key not in self._early_stoppers:
                self._early_stoppers[key] = create_early_stopper(algo)
            return self._early_stoppers[key]

    def get_early_stopping_rules(self, payload: Dict) -> Dict:
        spec = ExperimentSpec.from_dict(payload["experiment"])
        trials = [Trial.from_dict(t) for t in payload.get("trials", [])]
        assert spec.early_stopping is not None
        stopper = self._early_stopper(spec.early_stopping.algorithm_name, spec.name)
        if self.store is None:
            raise RuntimeError("early stopping service requires an observation store")
        rules = stopper.get_early_stopping_rules(spec, trials, self.store)
        return {"earlyStoppingRules": [r.to_dict() for r in rules]}

    def validate_early_stopping_settings(self, payload: Dict) -> Dict:
        spec = ExperimentSpec.from_dict(payload["experiment"])
        assert spec.early_stopping is not None
        self._early_stopper(spec.early_stopping.algorithm_name, spec.name).validate_settings(spec)
        return {}

    def set_trial_status(self, payload: Dict) -> Dict:
        """medianstop SetTrialStatus (service.py:193-247): mark EarlyStopped.
        In-process orchestrators read trial_status_overrides. gRPC handlers
        run on a thread pool, so the shared override map is written under
        the service lock like the suggester/stopper registries."""
        with self._lock:
            self.trial_status_overrides[payload["trialName"]] = (
                TrialCondition.EARLY_STOPPED.value
            )
        return {}

    # -- DBManager service (api.proto:13-31) ---------------------------------

    def report_observation_log(self, payload: Dict) -> Dict:
        # Idempotent receiver: the client retries UNAVAILABLE (reference
        # 10×/3s policy), and a server that committed the write but died
        # before responding would otherwise double-append the same rows on
        # the retry. At-least-once delivery + exact-duplicate drop here =
        # effectively-once; (timestamp, metric, value) triples are unique
        # for genuine observations (collectors stamp scrape/log time).
        assert self.store is not None
        trial = payload["trialName"]
        logs = [
            MetricLog(float(l["timestamp"]), l["metricName"], str(l["value"]))
            for l in payload.get("metricLogs", [])
        ]
        self._record_rpc_span(
            "rpc.report_observation_log", payload, trial=trial, rows=len(logs)
        )
        if not logs:
            return {}
        # a duplicate of an incoming row necessarily shares its timestamp,
        # so the dedup read only needs rows from the batch's window — the
        # (trial, time) index answers it without rescanning the full log
        existing = {
            (r.timestamp, r.metric_name, r.value)
            for r in self.store.get_observation_log(
                trial, start_time=min(l.timestamp for l in logs)
            )
        }
        fresh = [l for l in logs if (l.timestamp, l.metric_name, l.value) not in existing]
        if fresh:
            self.store.report_observation_log(trial, fresh)
        return {}

    def report_many_observation_logs(self, payload: Dict) -> Dict:
        """Batched DBManager write — the group-commit unit over the wire.
        One request carries many trials' rows (``entries``: a list of
        ReportObservationLog payloads); each entry keeps the idempotent
        exact-duplicate drop of the single-trial receiver, so a retried
        batch after a half-committed crash never double-appends."""
        for entry in payload.get("entries", []):
            if payload.get("traceparent") and "traceparent" not in entry:
                entry = dict(entry, traceparent=payload["traceparent"])
            self.report_observation_log(entry)
        return {}

    def truncate_observation_log(self, payload: Dict) -> Dict:
        """Crash-recovery truncation (controller/recovery.py) over the wire:
        drop rows strictly newer than ``afterTime`` — a failed-over replica
        resuming a trial from its checkpoint uses this through the same
        store interface as the local path."""
        assert self.store is not None
        dropped = self.store.truncate_observation_log(
            payload["trialName"], float(payload["afterTime"])
        )
        return {"dropped": int(dropped)}

    def get_observation_log(self, payload: Dict) -> Dict:
        assert self.store is not None
        rows = self.store.get_observation_log(
            payload["trialName"],
            metric_name=payload.get("metricName"),
            start_time=payload.get("startTime"),
            end_time=payload.get("endTime"),
            limit=payload.get("limit"),
        )
        return {
            "metricLogs": [
                {"timestamp": r.timestamp, "metricName": r.metric_name, "value": r.value}
                for r in rows
            ]
        }

    def get_folded_observation(self, payload: Dict) -> Dict:
        """Folded {min,max,latest} per requested metric — O(metrics) on
        stores with the incremental fold index, so remote pollers stop
        shipping (and re-folding) whole observation logs per poll."""
        assert self.store is not None
        obs = self.store.folded(
            payload["trialName"], list(payload.get("metricNames", []))
        )
        return {"metrics": [m.to_dict() for m in obs.metrics]}

    def delete_observation_log(self, payload: Dict) -> Dict:
        assert self.store is not None
        self.store.delete_observation_log(payload["trialName"])
        return {}

    @staticmethod
    def _record_rpc_span(name: str, payload: Dict, **attrs) -> None:
        """Rejoin point for traced clients: a request carrying a
        ``traceparent`` (W3C-style, issued by the controller's tracer) lands
        a server-side span parented into the caller's trial trace."""
        from ..tracing import default_tracer, parse_traceparent

        ctx = parse_traceparent(payload.get("traceparent"))
        if ctx is None:
            return
        tracer = default_tracer()
        if not tracer.enabled:
            return
        trace_id, parent_id = ctx
        span = tracer.start_span(name, "_rpc", trace_id, parent_id, attrs=attrs)
        tracer.end_span(span)

    # ------------------------------------------------------------------

    METHODS = {
        "GetSuggestions": get_suggestions,
        "ValidateAlgorithmSettings": validate_algorithm_settings,
        "GetEarlyStoppingRules": get_early_stopping_rules,
        "ValidateEarlyStoppingSettings": validate_early_stopping_settings,
        "SetTrialStatus": set_trial_status,
        "ReportObservationLog": report_observation_log,
        "ReportManyObservationLogs": report_many_observation_logs,
        "GetObservationLog": get_observation_log,
        "GetFoldedObservation": get_folded_observation,
        "TruncateObservationLog": truncate_observation_log,
        "DeleteObservationLog": delete_observation_log,
    }


def _make_handler(servicer: ApiServicer):
    def handle(method_name: str):
        fn = ApiServicer.METHODS[method_name]

        def unary_unary(request: bytes, context) -> bytes:
            try:
                payload = json.loads(request.decode()) if request else {}
                return _json_bytes(fn(servicer, payload))
            except (ValueError, KeyError) as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except Exception as e:  # pragma: no cover - defensive
                context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

        return grpc.unary_unary_rpc_method_handler(
            unary_unary, request_deserializer=_ident, response_serializer=_ident
        )

    return grpc.method_handlers_generic_handler(
        SERVICE, {name: handle(name) for name in ApiServicer.METHODS}
    )


def serve(
    servicer: Optional[ApiServicer] = None,
    port: int = DEFAULT_PORT,
    store: Optional[ObservationStore] = None,
    max_workers: int = 8,
    block: bool = False,
) -> grpc.Server:
    """Start the service — the cmd/suggestion/*/main.py pattern (ThreadPool
    gRPC server + health service on 0.0.0.0:<port>)."""
    servicer = servicer or ApiServicer(store=store)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_make_handler(servicer),))
    try:
        from grpc_health.v1 import health, health_pb2, health_pb2_grpc

        health_servicer = health.HealthServicer()
        health_pb2_grpc.add_HealthServicer_to_server(health_servicer, server)
        health_servicer.set("", health_pb2.HealthCheckResponse.SERVING)
    except ImportError:
        pass
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise RuntimeError(f"failed to bind gRPC server to port {port}")
    server.bound_port = bound  # actual port (when port=0 the OS picks one)
    server.start()
    if block:
        server.wait_for_termination()
    return server


# The reference retries every suggestion-client RPC 10 times on a 3s period
# (pkg/controller.v1beta1/consts/const.go:88-91 DefaultGRPCRetryAttempts /
# DefaultGRPCRetryPeriod, wired via grpc_retry in suggestionclient.go:57-61).
DEFAULT_RETRY_ATTEMPTS = 10
DEFAULT_RETRY_PERIOD_S = 3.0

_RETRYABLE = (grpc.StatusCode.UNAVAILABLE,)


class ApiClient:
    """JSON-bytes client for the service above.

    Retry semantics match the reference's grpc_retry interceptor: up to
    ``retries`` attempts spaced ``retry_period`` apart, retrying only on
    UNAVAILABLE (server down/restarting). gRPC Python does NOT retry by
    default — and its in-channel retryPolicy hard-caps maxAttempts at 5 —
    so the 10×/3s reference policy is an explicit loop here, not channel
    config. Non-retryable codes (e.g. INVALID_ARGUMENT from validation)
    propagate immediately.
    """

    def __init__(
        self,
        address: str = f"localhost:{DEFAULT_PORT}",
        timeout: float = 60.0,
        retries: int = DEFAULT_RETRY_ATTEMPTS,
        retry_period: float = DEFAULT_RETRY_PERIOD_S,
    ):
        self.channel = grpc.insecure_channel(address)
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.retry_period = retry_period

    def _call(self, method: str, payload: Dict) -> Dict:
        import time

        rpc = self.channel.unary_unary(
            f"/{SERVICE}/{method}", request_serializer=_ident, response_deserializer=_ident
        )
        data = _json_bytes(payload)
        last_err: Optional[grpc.RpcError] = None
        for attempt in range(self.retries):
            try:
                out = rpc(data, timeout=self.timeout)
                return json.loads(out.decode()) if out else {}
            except grpc.RpcError as e:
                if e.code() not in _RETRYABLE or attempt == self.retries - 1:
                    raise
                last_err = e
                time.sleep(self.retry_period)
        raise last_err  # unreachable; loop either returns or raises

    def close(self) -> None:
        self.channel.close()


class RemoteSuggester(Suggester):
    """Suggester backed by a remote service — lets the controller use
    out-of-process algorithms exactly like the reference's per-experiment
    suggestion pods. The 10×/3s UNAVAILABLE retry from
    consts/const.go:88-91 lives in ApiClient._call, so a suggester that is
    restarting mid-experiment is retried instead of failing the reconcile."""

    name = "remote"

    def __init__(
        self,
        address: str,
        timeout: float = 60.0,
        retries: int = DEFAULT_RETRY_ATTEMPTS,
        retry_period: float = DEFAULT_RETRY_PERIOD_S,
    ):
        self.client = ApiClient(address, timeout=timeout, retries=retries, retry_period=retry_period)

    def get_suggestions(self, request: SuggestionRequest):
        from ..suggest.base import SuggestionReply

        out = self.client._call(
            "GetSuggestions",
            _request(
                request.experiment,
                request.trials,
                request.current_request_number,
                request.total_request_number,
            ),
        )
        return SuggestionReply(
            assignments=[TrialAssignment.from_dict(a) for a in out.get("assignments", [])],
            algorithm_settings=dict(out.get("algorithmSettings", {})),
            search_ended=bool(out.get("searchEnded", False)),
        )

    def validate_algorithm_settings(self, experiment: ExperimentSpec) -> None:
        try:
            self.client._call(
                "ValidateAlgorithmSettings", {"experiment": experiment.to_dict()}
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.INVALID_ARGUMENT:
                raise ValueError(e.details()) from e
            raise


class RemoteObservationStore(ObservationStore):
    """ObservationStore backed by the remote DBManager — what a trial pod on
    another host uses to push metrics (api/report_metrics.py push mode)."""

    def __init__(
        self,
        address: str,
        timeout: float = 30.0,
        retries: int = DEFAULT_RETRY_ATTEMPTS,
        retry_period: float = DEFAULT_RETRY_PERIOD_S,
    ):
        self.client = ApiClient(
            address, timeout=timeout, retries=retries, retry_period=retry_period
        )

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        from ..tracing import current_traceparent

        payload = {
            "trialName": trial_name,
            "metricLogs": [
                {"timestamp": l.timestamp, "metricName": l.metric_name, "value": l.value}
                for l in logs
            ],
        }
        tp = current_traceparent()
        if tp:
            payload["traceparent"] = tp  # rejoined server-side (api servicer)
        self.client._call("ReportObservationLog", payload)

    def report_many(self, entries) -> None:
        """Batched push: one RPC per group-commit batch (the
        BufferedObservationStore flusher's drain unit)."""
        batch = [
            {
                "trialName": t,
                "metricLogs": [
                    {"timestamp": l.timestamp, "metricName": l.metric_name,
                     "value": l.value}
                    for l in logs
                ],
            }
            for t, logs in entries
            if logs
        ]
        if batch:
            self.client._call("ReportManyObservationLogs", {"entries": batch})

    def truncate_observation_log(self, trial_name: str, after_time: float) -> int:
        out = self.client._call(
            "TruncateObservationLog",
            {"trialName": trial_name, "afterTime": after_time},
        )
        return int(out.get("dropped", 0))

    def get_observation_log(
        self, trial_name, metric_name=None, start_time=None, end_time=None, limit=None
    ):
        out = self.client._call(
            "GetObservationLog",
            {
                "trialName": trial_name,
                "metricName": metric_name,
                "startTime": start_time,
                "endTime": end_time,
                "limit": limit,
            },
        )
        return [
            MetricLog(float(l["timestamp"]), l["metricName"], str(l["value"]))
            for l in out.get("metricLogs", [])
        ]

    def folded(self, trial_name, metric_names):
        """Server-side fold: one small reply instead of the whole log."""
        from ..api.spec import Metric, Observation

        out = self.client._call(
            "GetFoldedObservation",
            {"trialName": trial_name, "metricNames": list(metric_names)},
        )
        return Observation(metrics=[Metric.from_dict(m) for m in out.get("metrics", [])])

    def delete_observation_log(self, trial_name: str) -> None:
        self.client._call("DeleteObservationLog", {"trialName": trial_name})

    def close(self) -> None:
        self.client.close()
