"""Ring attention — sequence/context parallelism over a named mesh axis.

First-class long-context support (task requirement; absent from the reference,
which has no model code — SURVEY.md §5 "long-context"): each device holds one
sequence block of Q/K/V; K/V blocks rotate around the ring via
``jax.lax.ppermute`` (XLA collective-permute over ICI) while a flash-style
online softmax accumulates the output, so attention over sequence length T
costs O(T/p) memory per device and fully overlaps compute with neighbor
transfers.

Differentiable end-to-end (pure jax ops through shard_map/fori_loop), so the
same code path serves training. The blockwise update is the standard
safe-softmax recurrence:

    m' = max(m, rowmax(S))
    l' = l * e^{m-m'} + rowsum(e^{S-m'})
    o' = o * e^{m-m'} + e^{S-m'} V

Causal masking uses global positions derived from the device's ring index, so
a sharded causal LM matches the dense reference exactly.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_attn_update(q, k_blk, v_blk, o, m, l, q_offset, k_offset, causal, scale):
    """One ring step: accumulate attention of local q against one K/V block.

    q: [B, Tq, H, D]; k_blk/v_blk: [B, Tk, H, D]; o: [B, Tq, H, D];
    m, l: [B, H, Tq].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale  # [B, H, Tq, Tk]
    if causal:
        tq, tk = q.shape[1], k_blk.shape[1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where((k_pos > q_pos)[None, None], NEG_INF, s)
    m_new = jnp.maximum(m, s.max(axis=-1))          # [B, H, Tq]
    # guard fully-masked rows (m_new == NEG_INF): exp underflows to 0 safely
    p = jnp.exp(s - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False):
    """Body to run INSIDE shard_map over ``axis_name``: local blocks of
    q/k/v shaped [B, T_local, H, D]."""
    p_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    q_offset = my_idx * t_local

    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def body(i, carry):
        k_blk, v_blk, o, m, l = carry
        src = (my_idx - i) % p_size            # block index currently held
        o, m, l = _block_attn_update(
            q, k_blk, v_blk, o, m, l, q_offset, src * t_local, causal, scale
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, o, m, l

    o0 = jnp.zeros_like(q)
    # Derive the accumulators from q so they inherit its varying-manual-axes
    # type (fresh constants would mismatch the loop carry under shard_map).
    base = q[:, :, :, 0].transpose(0, 2, 1)  # [B, H, Tq], varying like q
    m0 = jnp.full_like(base, NEG_INF)
    l0 = jnp.zeros_like(base)
    _, _, o, m, l = jax.lax.fori_loop(0, p_size, body, (k, v, o0, m0, l0))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o / denom


def dense_attention(q, k, v, causal: bool = False):
    """Reference (unsharded) attention, same layout [B, T, H, D]."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def ring_attention(
    q,
    k,
    v,
    mesh,
    causal: bool = False,
    seq_axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: str = "model",
):
    """shard_map wrapper: q/k/v [B, T, H, D] sharded T over ``seq_axis``,
    B over ``batch_axes``, H over ``head_axis``. Falls back to dense attention
    when the mesh has no sequence sharding."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    if sizes.get(seq_axis, 1) == 1:
        return dense_attention(q, k, v, causal=causal)

    spec = P(tuple(a for a in batch_axes if sizes.get(a, 1) > 1) or None, seq_axis, head_axis, None)
    fn = jax.shard_map(
        functools.partial(ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
