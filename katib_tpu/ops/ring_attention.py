"""Ring attention — sequence/context parallelism over a named mesh axis.

First-class long-context support (task requirement; absent from the reference,
which has no model code — SURVEY.md §5 "long-context"): each device holds one
sequence block of Q/K/V; K/V blocks rotate around the ring via
``jax.lax.ppermute`` (XLA collective-permute over ICI) while a flash-style
online softmax accumulates the output, so attention over sequence length T
costs O(T/p) memory per device and fully overlaps compute with neighbor
transfers.

Differentiable end-to-end (pure jax ops through shard_map/fori_loop), so the
same code path serves training. The blockwise update is the standard
safe-softmax recurrence:

    m' = max(m, rowmax(S))
    l' = l * e^{m-m'} + rowsum(e^{S-m'})
    o' = o * e^{m-m'} + e^{S-m'} V

Causal masking uses global positions derived from the device's ring index, so
a sharded causal LM matches the dense reference exactly.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _ring_fwd_loop(q, k, v, axis_name: str, causal: bool, interpret: Optional[bool] = None):
    """Forward ring: per step, flash-attend local Q against the held K/V
    block (Pallas kernel on TPU, dense+lse fallback elsewhere) and fold the
    normalized block output into the running result by logsumexp weights.
    Returns (o [B,T,H,D], lse [B,T,H])."""
    from .flash_attention import flash_attention_with_lse, merge_attention_blocks

    p_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def masked_block():
        return (
            jnp.zeros_like(q),
            jnp.full_like(q[..., 0], NEG_INF).astype(jnp.float32),
        )

    def body(i, carry):
        k_blk, v_blk, o, lse = carry
        src = (my_idx - i) % p_size  # block index currently held
        if causal:
            o_b, lse_b = jax.lax.cond(
                src == my_idx,
                lambda: flash_attention_with_lse(q, k_blk, v_blk, causal=True, interpret=interpret),
                lambda: jax.lax.cond(
                    src < my_idx,
                    lambda: flash_attention_with_lse(q, k_blk, v_blk, causal=False, interpret=interpret),
                    masked_block,  # strictly-future block: contributes nothing
                ),
            )
        else:
            o_b, lse_b = flash_attention_with_lse(q, k_blk, v_blk, causal=False, interpret=interpret)
        o, lse = merge_attention_blocks(o, lse, o_b, lse_b)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, o, lse

    # accumulators derived from q so they inherit its varying-manual-axes
    # type under shard_map (fresh constants would mismatch the loop carry)
    o0, lse0 = masked_block()
    _, _, o, lse = jax.lax.fori_loop(0, p_size, body, (k, v, o0, lse0))
    return o, lse


def _ring_bwd_loop(q, k, v, o, lse, do, axis_name: str, causal: bool, interpret: Optional[bool] = None):
    """Backward ring (standard flash/ring backward): with the global
    logsumexp, every block's gradient contribution is independent
    (p = exp(s - lse); ds = p * (dp - delta)), computed per rotation by
    flash_block_grads — Pallas _bwd kernels on TPU, dense f32 math at
    HIGHEST precision elsewhere. dq accumulates locally; per-block dk/dv
    accumulators rotate with their block and arrive home after a full
    rotation. Strictly-future blocks are skipped in the causal case (their
    p is identically zero)."""
    from .flash_attention import flash_block_grads

    p_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def body(i, carry):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        src = (my_idx - i) % p_size

        def block(blk_causal):
            return lambda: flash_block_grads(
                q, k_blk, v_blk, o, lse, do, causal=blk_causal,
                interpret=interpret,
            )

        if causal:
            dq_c, dk_c, dv_c = jax.lax.cond(
                src == my_idx,
                block(True),
                lambda: jax.lax.cond(
                    src < my_idx,
                    block(False),
                    # strictly-future block: p == 0 everywhere, skip compute
                    lambda: (jnp.zeros_like(q), jnp.zeros_like(k_blk),
                             jnp.zeros_like(v_blk)),
                ),
            )
        else:
            dq_c, dk_c, dv_c = block(False)()
        dq = dq + dq_c.astype(dq.dtype)
        dk_blk = dk_blk + dk_c.astype(dk_blk.dtype)
        dv_blk = dv_blk + dv_c.astype(dv_blk.dtype)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        return k_blk, v_blk, dk_blk, dv_blk, dq

    zeros = jnp.zeros_like(q.astype(jnp.float32))
    _, _, dk, dv, dq = jax.lax.fori_loop(
        0, p_size, body, (k, v, zeros, zeros, zeros)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False, interpret: Optional[bool] = None):
    """Body to run INSIDE shard_map over ``axis_name``: local blocks of
    q/k/v shaped [B, T_local, H, D]. Forward uses the Pallas flash kernel
    per block on TPU; the custom VJP runs the ring backward from the saved
    global logsumexp, so the O(T^2) score matrix never materializes across
    the whole sequence in either direction."""

    @jax.custom_vjp
    def ring(q, k, v):
        o, _ = _ring_fwd_loop(q, k, v, axis_name, causal, interpret)
        return o

    def ring_fwd(q, k, v):
        o, lse = _ring_fwd_loop(q, k, v, axis_name, causal, interpret)
        return o, (q, k, v, o, lse)

    def ring_bwd(res, do):
        q, k, v, o, lse = res
        return _ring_bwd_loop(q, k, v, o, lse, do, axis_name, causal, interpret)

    ring.defvjp(ring_fwd, ring_bwd)
    return ring(q, k, v)


def dense_attention(q, k, v, causal: bool = False):
    """Reference (unsharded) attention, same layout [B, T, H, D]."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def ring_attention(
    q,
    k,
    v,
    mesh,
    causal: bool = False,
    seq_axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: str = "model",
):
    """shard_map wrapper: q/k/v [B, T, H, D] sharded T over ``seq_axis``,
    B over ``batch_axes``, H over ``head_axis``. Falls back to dense attention
    when the mesh has no sequence sharding."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    if sizes.get(seq_axis, 1) == 1:
        return dense_attention(q, k, v, causal=causal)

    # shard batch only over axes the batch size actually divides (anything
    # else computes replicated on those devices — correct, just redundant)
    from ..parallel.mesh import activation_batch_axes

    spec = P(
        activation_batch_axes(sizes, q.shape[0], batch_axes) or None,
        seq_axis, head_axis, None,
    )
    fn = jax.shard_map(
        functools.partial(ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,  # pallas_call outputs carry no vma annotation
    )
    return fn(q, k, v)
