"""DARTS operation set in flax — TPU re-design of the reference op library.

reference examples/v1beta1/trial-images/darts-cnn-cifar10/operations.py
(OPS dict: none, avg/max_pooling_3x3, skip_connection, separable_convolution
3x3/5x5, dilated_convolution 3x3/5x5).

TPU-first notes:
- NHWC layout everywhere (XLA's preferred conv layout on TPU).
- Normalization is stateless per-batch (train-mode BatchNorm with
  affine=False, no running stats): avoids mutable collections so the whole
  supernet stays a pure function — required for clean bilevel jax.grad and
  pjit sharding of the architect step.
- The mixed op evaluates every candidate and takes the alpha-weighted sum
  (one fused weighted add in XLA) rather than data-dependent branching,
  which would break tracing.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def batch_norm(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Per-batch normalization over N,H,W (affine=False train-mode BN)."""
    mean = x.mean(axis=(0, 1, 2), keepdims=True)
    var = x.var(axis=(0, 1, 2), keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps)


class MatmulConv(nn.Module):
    """Drop-in for nn.Conv (NHWC, SAME, no bias) lowered to an im2col matmul.

    DARTS search cells have tiny channel counts, and XLA:TPU's backward pass
    for direct low-channel convolutions compiles ~5x slower than the
    equivalent [B*H*W, C*kh*kw] x [C*kh*kw, F] GEMM — which is also the shape
    the MXU wants. 1x1 convs skip patch extraction entirely (stride by
    slicing + one einsum). Param name/shape match nn.Conv ('kernel',
    [kh, kw, C, F]) so genotypes/checkpoints are interchangeable."""

    features: int
    kernel_size: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    kernel_dilation: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        c = x.shape[-1]
        w = self.param(
            "kernel", nn.initializers.lecun_normal(), (kh, kw, c, self.features)
        )
        if (kh, kw) == (1, 1) and self.kernel_dilation == (1, 1):
            sh, sw = self.strides
            if (sh, sw) != (1, 1):
                x = x[:, ::sh, ::sw, :]
            return jnp.einsum("bhwc,cf->bhwf", x, w[0, 0])
        patches = jax.lax.conv_general_dilated_patches(
            x,
            (kh, kw),
            self.strides,
            "SAME",
            rhs_dilation=self.kernel_dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # [..., C*kh*kw] with feature order C x kh x kw
        wmat = w.transpose(2, 0, 1, 3).reshape(c * kh * kw, self.features)
        return patches @ wmat


class Zero(nn.Module):
    """operations.py Zero: multiply by 0, strided slice when reducing."""

    stride: int = 1

    @nn.compact
    def __call__(self, x):
        if self.stride == 1:
            return x * 0.0
        return x[:, :: self.stride, :: self.stride, :] * 0.0


class PoolBN(nn.Module):
    """operations.py PoolBN: avg/max pool 3x3 + BN."""

    pool_type: str  # "avg" | "max"
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        if self.pool_type == "avg":
            out = nn.avg_pool(x, (3, 3), strides=(self.stride, self.stride), padding="SAME")
        else:
            out = nn.max_pool(x, (3, 3), strides=(self.stride, self.stride), padding="SAME")
        return batch_norm(out)


class Identity(nn.Module):
    @nn.compact
    def __call__(self, x):
        return x


class FactorizedReduce(nn.Module):
    """operations.py FactorizedReduce: stride-2 via two offset 1x1 convs
    concatenated, then BN."""

    channels: int

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        h = self.channels // 2
        a = MatmulConv(h, (1, 1), strides=(2, 2), name="conv1")(x)
        b = MatmulConv(self.channels - h, (1, 1), strides=(2, 2), name="conv2")(
            x[:, 1:, 1:, :]
        )
        return batch_norm(jnp.concatenate([a, b], axis=-1))


class StdConv(nn.Module):
    """operations.py StdConv: ReLU - Conv - BN."""

    channels: int
    kernel_size: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = MatmulConv(
            self.channels,
            (self.kernel_size, self.kernel_size),
            strides=(self.stride, self.stride),
        )(x)
        return batch_norm(x)


class SepConv(nn.Module):
    """operations.py SepConv: two stacked (ReLU - depthwise - pointwise - BN)
    blocks, stride applied in the first."""

    channels: int
    kernel_size: int
    stride: int

    @nn.compact
    def __call__(self, x):
        for i, stride in enumerate((self.stride, 1)):
            x = nn.relu(x)
            x = nn.Conv(
                x.shape[-1],
                (self.kernel_size, self.kernel_size),
                strides=(stride, stride),
                padding="SAME",
                feature_group_count=x.shape[-1],
                use_bias=False,
                name=f"dw{i}",
            )(x)
            x = MatmulConv(self.channels, (1, 1), name=f"pw{i}")(x)
            x = batch_norm(x)
        return x


class DilConv(nn.Module):
    """operations.py DilConv: ReLU - dilated depthwise - pointwise - BN."""

    channels: int
    kernel_size: int
    stride: int
    dilation: int = 2

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = nn.Conv(
            x.shape[-1],
            (self.kernel_size, self.kernel_size),
            strides=(self.stride, self.stride),
            padding="SAME",
            kernel_dilation=(self.dilation, self.dilation),
            feature_group_count=x.shape[-1],
            use_bias=False,
            name="dw",
        )(x)
        x = MatmulConv(self.channels, (1, 1), name="pw")(x)
        return batch_norm(x)


def make_op(name: str, channels: int, stride: int) -> nn.Module:
    """operations.py OPS factory."""
    if name == "none":
        return Zero(stride=stride)
    if name == "avg_pooling_3x3":
        return PoolBN(pool_type="avg", stride=stride)
    if name == "max_pooling_3x3":
        return PoolBN(pool_type="max", stride=stride)
    if name == "skip_connection":
        return Identity() if stride == 1 else FactorizedReduce(channels=channels)
    if name == "separable_convolution_3x3":
        return SepConv(channels=channels, kernel_size=3, stride=stride)
    if name == "separable_convolution_5x5":
        return SepConv(channels=channels, kernel_size=5, stride=stride)
    if name == "dilated_convolution_3x3":
        return DilConv(channels=channels, kernel_size=3, stride=stride, dilation=2)
    if name == "dilated_convolution_5x5":
        return DilConv(channels=channels, kernel_size=5, stride=stride, dilation=2)
    raise ValueError(f"unknown DARTS operation {name!r}")


class MixedOp(nn.Module):
    """Continuous relaxation: alpha-weighted sum of all candidate ops
    (operations.py MixedOp)."""

    primitives: Sequence[str]
    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, weights):
        outs = [
            make_op(p, self.channels, self.stride)(x) for p in self.primitives
        ]
        stacked = jnp.stack(outs, axis=0)  # [n_ops, N, H, W, C]
        return jnp.tensordot(weights, stacked, axes=1)
