"""Flash attention — fused Pallas TPU kernels for the unsharded-sequence path.

The hot op of the transformer trial runtime (katib_tpu.models.transformer).
The reference has no kernel code at all (its trials delegate to
PyTorch/TF images — SURVEY.md §2.8/§2.9); on TPU the idiomatic equivalent is
a Pallas kernel that keeps the O(T^2) score matrix out of HBM entirely:
Q/K/V blocks stream HBM→VMEM, scores live only as a [block_q, block_k] VMEM
tile feeding the MXU, and the online-softmax recurrence

    m' = max(m, rowmax(S));  l' = l·e^{m−m'} + rowsum(e^{S−m'})
    acc' = acc·e^{m−m'} + e^{S−m'}·V

accumulates the output in fp32 scratch. The backward pass is the standard
two-kernel recomputation (dQ with KV innermost; dK/dV with Q innermost) from
the saved logsumexp — no attention matrix is ever materialized in either
direction.

Sequence-sharded attention is handled by katib_tpu.ops.ring_attention (the
ring schedule rotates K/V between devices); this kernel is the within-device
fast path and the two compose: ring for cross-device blocks, flash for the
local block compute.

Falls back to interpret mode off-TPU (CPU tests) and to dense attention for
shapes the tiling cannot cover (tiny or non-divisible sequence lengths).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30
_LANES = 128  # TPU lane width; scratch vectors are padded to this


def _dot_precision(dtype):
    """f32 blocks need HIGHEST precision or the MXU's bf16 decomposition
    drops ~3 decimal digits; bf16 blocks run at native MXU rate regardless."""
    return jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None


def _on_tpu() -> bool:
    try:
        from ..utils.backend import bounded_devices

        # bounded probe (KTI304): kernel-vs-interpret dispatch on a wedged
        # backend degrades to the dense path instead of hanging
        devices = bounded_devices()
        if not devices:
            return False
        d = devices[0]
        return "tpu" in d.platform.lower() or "TPU" in getattr(d, "device_kind", "")
    except Exception:
        return False


def _use_kernel(interpret: Optional[bool]) -> bool:
    """Three-state kernel dispatch shared by every flash entry point:
    ``True`` forces the Pallas path (interpret mode off-TPU — kernel tests),
    ``False`` forces the dense fallback, ``None`` auto-selects by backend
    (interpret-mode Pallas off-TPU is orders of magnitude slower than one
    fused XLA attention)."""
    return interpret is True or (interpret is not False and _on_tpu())


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal: bool, sm_scale: float, block_q: int, block_k: int,
                kv_steps: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: skip blocks strictly above the diagonal.
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        # dots stay in the input dtype (MXU does bf16 x bf16 -> f32 natively;
        # casting blocks to f32 first runs the MXU at the much slower f32
        # rate) — only the softmax recurrence is f32. f32 inputs request
        # HIGHEST precision so the MXU's bf16 decomposition keeps f32 fidelity.
        q = q_ref[0]                                # [bq, d]
        k = k_ref[0]                                # [bk, d]
        v = v_ref[0]                                # [bk, d]
        prec = _dot_precision(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec,
        ) * sm_scale                                # [bq, bk] f32
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos > q_pos, NEG_INF, s)

        m_prev = m_ref[:, 0:1]                      # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                      # [bq, bk] f32
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == kv_steps - 1)
    def _finish():
        l = l_ref[:, 0:1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, 0:1] + jnp.log(jnp.maximum(l, 1e-30))


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    """q/k/v: [BH, T, D] -> (o [BH, T, D], lse [BH, T])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    grid = (bh, t // block_q, t // block_k)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_steps=t // block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward kernels (recompute from saved logsumexp)
# ---------------------------------------------------------------------------

def _recompute_p_ds(q, k, v, do, lse, delta, qi, ki, causal, sm_scale,
                    block_q, block_k):
    """Shared bwd block math: p [bq,bk] and ds [bq,bk] (pre-scaled, f32).

    Dots take the blocks in their native dtype (bf16 MXU rate) and accumulate
    f32; only the elementwise recurrence is f32.
    """
    prec = _dot_precision(q.dtype)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=prec,
    ) * sm_scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos > q_pos, NEG_INF, s)
    p = jnp.exp(s - lse)                            # lse [bq, 1] broadcasts
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=prec,
    )                                               # [bq, bk]
    ds = p * (dp - delta) * sm_scale                # delta [bq, 1]
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, causal, sm_scale, block_q, block_k, kv_steps):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        _, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0], delta_ref[0], qi, ki, causal, sm_scale,
            block_q, block_k,
        )
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_dot_precision(k.dtype),
        )

    @pl.when(ki == kv_steps - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, causal, sm_scale, block_q, block_k, q_steps):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0], delta_ref[0], qi, ki, causal, sm_scale,
            block_q, block_k,
        )
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_dot_precision(do.dtype),
        )
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_dot_precision(q.dtype),
        )

    @pl.when(qi == q_steps - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    delta = jnp.sum(
        o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1, keepdims=True
    )  # [BH, T, 1]

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec_dq = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, kv_steps=t // block_k,
        ),
        grid=(bh, t // block_q, t // block_k),
        in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid iterates q blocks innermost for a fixed kv block.
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    row_spec2 = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, q_steps=t // block_q,
        ),
        grid=(bh, t // block_k, t // block_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper on [BH, T, D]
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_bhtd_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bhtd_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    # The backward kernels prefer symmetric MXU-sized tiles: measured on v5e
    # (T=2048 d=64 causal), fwd+bwd with the forward's asymmetric bq=512
    # runs 10% SLOWER than bq=bk=1024 despite the faster forward — so bwd
    # blocks are chosen independently of the forward's (BWD_BLOCK_CAP).
    t = q.shape[1]
    bwd_block = _auto_block(t, BWD_BLOCK_CAP)
    bq = bwd_block or block_q
    bk = bwd_block or block_k
    dq, dk, dv = _bwd(q, k, v, o, lse, do, causal, sm_scale, bq, bk,
                      interpret)
    return dq, dk, dv


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


def _auto_block(t: int, cap: int) -> Optional[int]:
    """Largest multiple of 128 that divides t, capped — big blocks keep the
    MXU busy (measured on v5e at T=2048 d=64: 1024-blocks are 5.6x faster
    than 128-blocks and 2.3x faster than XLA dense attention). None when no
    lane-aligned tiling exists (caller falls back to dense)."""
    for b in range(min(cap, t) // 128 * 128, 127, -128):
        if t % b == 0:
            return b
    return None


FWD_BLOCK_Q_CAP = 512   # measured v5e sweep (T=2048 d=64 causal): bq=512/
FWD_BLOCK_K_CAP = 1024  # bk=1024 runs 1.6x faster than symmetric 1024 blocks
                        # (0.47ms vs 0.74ms) and is never worse at T=1024/4096;
                        # the smaller Q tile pipelines better against the
                        # K-innermost grid while K blocks stay MXU-sized
BWD_BLOCK_CAP = 1024    # backward tiles stay symmetric/large (see
                        # _flash_bhtd_bwd: small Q tiles regress fwd+bwd 10%)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused attention on [B, T, H, D] (same layout as ring/dense attention).

    Differentiable (custom VJP, recompute-based backward). Forward block
    sizes default to the largest dividing multiple of 128, asymmetric
    bq<=FWD_BLOCK_Q_CAP (512) / bk<=FWD_BLOCK_K_CAP (1024) per the measured
    v5e sweep; the backward kernels pick their own symmetric <=1024 tiles
    regardless of block_q/block_k (see _flash_bhtd_bwd). Sequences the
    tiling cannot cover (T < 2 MXU rows or not a multiple of 128) fall back
    to dense attention — semantics are identical.
    """
    from .ring_attention import dense_attention

    b, t, h, d = q.shape
    block_q = min(block_q, t) if block_q else (_auto_block(t, FWD_BLOCK_Q_CAP) or t + 1)
    block_k = min(block_k, t) if block_k else (_auto_block(t, FWD_BLOCK_K_CAP) or t + 1)

    def dense_fallback():
        # dense_attention hard-codes 1/sqrt(d); fold a custom sm_scale into q
        # so fallback results match the kernel on every platform
        qs = q if sm_scale is None else q * (sm_scale * math.sqrt(d))
        return dense_attention(qs, k, v, causal=causal)

    if t % block_q or t % block_k or t < 16 or not _use_kernel(interpret):
        return dense_fallback()
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    o = _flash_bhtd(
        to_bhtd(q), to_bhtd(k), to_bhtd(v),
        causal, float(sm_scale), block_q, block_k, bool(interpret),
    )
    return o.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Like flash_attention but also returns the per-row logsumexp
    ([B, T, H], f32) so partial attentions over different K/V blocks can be
    merged exactly — the primitive ring attention builds on (each ring step
    attends the local Q against one rotating K/V block, then folds the
    normalized block output into the running result via the lse weights).

    Differentiation note: the merge path re-derives gradients through the
    *fallback* expression; the Pallas fast path is forward-only here, so
    callers that need gradients under jit on TPU go through the dense
    fallback math (ring attention's callers differentiate the merged
    expression, which XLA fuses per block anyway).
    """
    b, t, h, d = q.shape
    tk = k.shape[1]
    if causal and tk != t:
        raise ValueError(
            f"causal flash_attention_with_lse needs equal q/k lengths, got {t} vs {tk}"
        )
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    use_kernel = False
    bq = min(block_q, t) if block_q else _auto_block(t, FWD_BLOCK_Q_CAP)
    bk = min(block_k, tk) if block_k else _auto_block(tk, FWD_BLOCK_K_CAP)
    if (
        tk == t  # the kernel grid assumes equal q/kv lengths
        and bq and bk and t % bq == 0 and tk % bk == 0 and t >= 16
    ):
        use_kernel = _use_kernel(interpret)

    if use_kernel:
        def to_bhtd(x):
            return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

        o, lse = _fwd(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, float(sm_scale),
            bq, bk, bool(interpret),
        )
        o = o.reshape(b, h, t, d).transpose(0, 2, 1, 3)
        lse = lse.reshape(b, h, t).transpose(0, 2, 1)  # [B, T, H]
        return o, lse

    # dense fallback with explicit lse (differentiable everywhere); f32 dots
    # request HIGHEST so the TPU MXU decomposition keeps f32 fidelity
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", (p / l).astype(q.dtype), v,
        precision=jax.lax.Precision.HIGHEST,
    )
    lse = (m + jnp.log(l))[..., 0].transpose(0, 2, 1)  # [B, T, H]
    return o, lse


def flash_block_grads(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    o: jnp.ndarray,
    lse: jnp.ndarray,
    do: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-block gradients against a GLOBAL logsumexp: with p = exp(s - lse)
    every K/V block's (dq, dk, dv) contribution is independent, so ring
    attention's backward can call this once per rotation. Layout
    [B, T, H, D]; lse [B, T, H] f32. Uses the Pallas _bwd kernels on TPU
    (scores never materialize), dense f32 math elsewhere. ``interpret=True``
    forces the kernel path in Pallas interpret mode (CI coverage of the ring
    backward's kernel glue off-TPU); ``interpret=False`` forces the dense
    fallback."""
    b, t, h, d = q.shape
    if k.shape[1] != t:
        raise ValueError("flash_block_grads needs equal q/k block lengths")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    bq = _auto_block(t, 1024)
    use_kernel = bq and t % bq == 0 and t >= 16 and _use_kernel(interpret)
    if use_kernel:
        def to_bhtd(x):
            return x.transpose(0, 2, 1, 3).reshape(b * h, t, x.shape[-1])

        lse_bhtd = lse.transpose(0, 2, 1).reshape(b * h, t, 1)
        dq, dk, dv = _bwd(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), to_bhtd(o), lse_bhtd,
            to_bhtd(do), causal, float(sm_scale), bq, bq, bool(interpret),
        )
        back = lambda x: x.reshape(b, h, t, d).transpose(0, 2, 1, 3)
        return back(dq), back(dk), back(dv)

    prec = jax.lax.Precision.HIGHEST
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf, precision=prec) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - lse.transpose(0, 2, 1)[..., None])
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)          # [B, T, H]
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf, precision=prec)
    ds = p * (dp - delta.transpose(0, 2, 1)[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf, precision=prec)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf, precision=prec)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof, precision=prec)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def merge_attention_blocks(
    o1: jnp.ndarray, lse1: jnp.ndarray, o2: jnp.ndarray, lse2: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold two normalized partial attentions (over disjoint K/V blocks) into
    one: o = softmax-weighted combination, lse = log(e^lse1 + e^lse2).
    o: [B, T, H, D]; lse: [B, T, H] f32. Fully-masked partials carry
    lse = NEG_INF (finite −1e30, not −inf) and drop out exactly."""
    m = jnp.maximum(lse1, lse2)
    both_masked = m <= NEG_INF  # masked lse is the FINITE sentinel NEG_INF
    m_safe = jnp.where(both_masked, 0.0, m)  # avoid exp(-1e30 - -1e30) = 1 drift
    w1 = jnp.exp(lse1 - m_safe)
    w2 = jnp.exp(lse2 - m_safe)
    denom = jnp.maximum(w1 + w2, 1e-30)
    o = (
        o1.astype(jnp.float32) * (w1 / denom)[..., None]
        + o2.astype(jnp.float32) * (w2 / denom)[..., None]
    ).astype(o1.dtype)
    lse = m_safe + jnp.log(denom)
    lse = jnp.where(both_masked, NEG_INF, lse)
    return o, lse


def sharded_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    causal: bool = False,
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: str = "model",
    **kw,
) -> jnp.ndarray:
    """shard_map wrapper for the seq-unsharded case: batch over data/fsdp,
    heads over model — each device runs the flash kernel on its local heads
    with no collectives (heads are independent)."""
    from jax.sharding import PartitionSpec as P

    shard_map = jax.shard_map

    from ..parallel.mesh import mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    b, _, h, _ = q.shape
    # Shard only over axes the actual shape divides; anything else computes
    # replicated on those devices (correct, just redundant).
    from ..parallel.mesh import activation_batch_axes

    batch = activation_batch_axes(sizes, b, batch_axes) or None
    head_size = sizes.get(head_axis, 1)
    head = head_axis if head_size > 1 and h % head_size == 0 else None
    spec = P(batch, None, head, None)
    fn = shard_map(
        functools.partial(flash_attention, causal=causal, **kw),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,  # pallas_call outputs carry no vma annotation
    )
    return fn(q, k, v)
