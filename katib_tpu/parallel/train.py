"""Distributed training-step builder: pjit over a named mesh with full
dp/fsdp/tp/sp shardings.

The TPU-native counterpart of the reference's delegated distributed trials
(PyTorchJob-DDP / MPIJob-Horovod, SURVEY.md §2.9): one jitted step where XLA
inserts every collective — gradient psum/reduce-scatter over 'data'/'fsdp',
activation all-gathers for TP ('model'), ring collective-permutes for
sequence parallelism ('seq').
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import optax

from ..models.transformer import (
    TransformerConfig,
    TransformerLM,
    param_sharding_rules,
)


def lm_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()


def _default_device():
    """First device of the initialized backend, through the bounded probe
    (utils/backend.py — KTI304): inside a train-step builder the backend is
    normally already up, so this is one cached-verdict check and a direct
    call; on a wedged backend it raises fast instead of hanging the trial."""
    from ..utils.backend import require_devices

    return require_devices()[0]


def make_lm_train_step(
    config: TransformerConfig,
    mesh,
    learning_rate: float = 1e-3,
    seed: int = 0,
):
    """Returns (params, opt_state, step_fn, positions_fn).

    step_fn(params, opt_state, tokens, targets) -> (params, opt_state, loss),
    jitted with NamedShardings: tokens/targets P(('data','fsdp'), 'seq'),
    params per katib_tpu.models.transformer.param_sharding_rules.
    """
    import flax
    from jax.sharding import NamedSharding, PartitionSpec as P

    # Single-device mesh: GSPMD partitioning buys nothing on one chip and the
    # sharded-array dispatch path is dramatically slower on tunneled TPU
    # backends (measured 160x on v5e via axon: 28ms/step plain jit vs 4.5s
    # with a 1-device NamedSharding). Build the plain jit step instead —
    # semantics are identical, collectives are no-ops on one device.
    single_device = mesh is None or int(mesh.devices.size) == 1
    target_device = None if mesh is None else mesh.devices.reshape(-1)[0]
    multiprocess = not single_device and jax.process_count() > 1

    model = TransformerLM(config, mesh=None if single_device else mesh)
    sample_tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    from ..utils.modelinit import jitted_init

    if not multiprocess:
        # (multi-process ranks can't pin another process's device — and their
        # params are created globally sharded below, not materialized here)
        params = jitted_init(
            model, jax.random.PRNGKey(seed), sample_tokens,
            device=target_device if single_device else _default_device(),
        )

    tx = optax.adamw(learning_rate, weight_decay=0.01)

    if single_device:
        # Keep params and batches UNCOMMITTED (no device_put): on tunneled
        # TPU backends, executing with committed input arrays takes a ~45x
        # slower dispatch path (measured 562ms vs 12ms per identical step).
        # Placement on a non-default chip (a trial gang-allocated to chip k
        # of a multi-chip host) is preserved by running creation and every
        # step under jax.default_device(target) instead of committing.
        batch_mesh = None
    elif multiprocess:
        # Multi-host gang (MultiHostExecutor workers): params must be born
        # globally sharded — device_put can't target another process's
        # devices. jit with out_shardings materializes each process's
        # addressable shards directly from one traced init.
        shapes = jax.eval_shape(
            lambda k: model.init(k, sample_tokens)["params"], jax.random.PRNGKey(seed)
        )
        flat_specs = {
            k: NamedSharding(mesh, param_sharding_rules(k))
            for k in flax.traverse_util.flatten_dict(shapes)
        }
        sharding_tree = flax.traverse_util.unflatten_dict(flat_specs)
        init_fn = jax.jit(
            lambda k: model.init(k, sample_tokens)["params"],
            out_shardings=sharding_tree,
        )
        params = init_fn(jax.random.PRNGKey(seed))
        batch_mesh = mesh
    else:
        # shard params + opt state
        flat_specs = {
            k: param_sharding_rules(k)
            for k in flax.traverse_util.flatten_dict(params)
        }
        param_specs = flax.traverse_util.unflatten_dict(flat_specs)
        params = jax.tree.map(
            lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
            params,
            param_specs,
            is_leaf=lambda x: not isinstance(x, dict),
        )
        batch_mesh = mesh

    # Non-default target chip: uncommitted execution follows the *default*
    # device, so pin creation and every step with jax.default_device —
    # placement without the committed-array dispatch penalty.
    pin_device = (
        target_device
        if single_device
        and target_device is not None
        and target_device != _default_device()
        else None
    )

    if pin_device is None:
        opt_state = tx.init(params)
    else:
        with jax.default_device(pin_device):
            opt_state = tx.init(params)

    def step(params, opt_state, tokens, targets, positions):
        def loss_fn(p):
            if config.num_experts > 0:
                from ..models.transformer import collect_moe_aux

                logits, mutated = model.apply(
                    {"params": p}, tokens, positions, mutable=["intermediates"]
                )
                return lm_loss(logits, targets) + collect_moe_aux(mutated)
            logits = model.apply({"params": p}, tokens, positions)
            return lm_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jitted_step = jax.jit(step, donate_argnums=(0, 1))

    if pin_device is None:
        step_fn = jitted_step
    else:
        def step_fn(*args):
            with jax.default_device(pin_device):
                return jitted_step(*args)

    def put_batch(tokens, targets, positions=None):
        import contextlib
        import numpy as np

        if positions is None:
            b, t = tokens.shape
            positions = np.broadcast_to(np.arange(t, dtype="int32"), (b, t))
        if batch_mesh is None:
            ctx = (
                jax.default_device(pin_device)
                if pin_device is not None
                else contextlib.nullcontext()
            )
            with ctx:
                return jnp.asarray(tokens), jnp.asarray(targets), jnp.asarray(positions)
        from ..parallel.mesh import batch_spec

        batch_sharding = NamedSharding(
            batch_mesh, batch_spec(tokens.shape[0], batch_mesh)
        )
        return (
            jax.device_put(tokens, batch_sharding),
            jax.device_put(targets, batch_sharding),
            jax.device_put(positions, batch_sharding),
        )

    return params, opt_state, step_fn, put_batch


def run_lm_trial(assignments: Dict[str, str], ctx=None) -> None:
    """HPO trial over the distributed LM: hyperparameters learning_rate,
    embed_dim, num_layers; reports per-epoch loss. Builds its mesh from the
    trial's gang-allocated devices (dp [+ tp/sp via assignments])."""
    import numpy as np

    from .mesh import make_mesh

    lr = float(assignments.get("learning_rate", "1e-3"))
    embed_dim = int(assignments.get("embed_dim", "128"))
    num_layers = int(assignments.get("num_layers", "2"))
    num_heads = int(assignments.get("num_heads", "4"))
    tp = int(assignments.get("tensor_parallel", "1"))
    sp = int(assignments.get("sequence_parallel", "1"))
    steps = int(assignments.get("num_steps", "20"))
    batch = int(assignments.get("batch_size", "8"))
    seq_len = int(assignments.get("seq_len", "128"))
    vocab = int(assignments.get("vocab_size", "512"))

    devices = ctx.jax_devices() or None if ctx is not None else None
    mesh = make_mesh(devices, model=tp, seq=sp)

    config = TransformerConfig(
        vocab_size=vocab,
        embed_dim=embed_dim,
        num_layers=num_layers,
        num_heads=num_heads,
        max_seq_len=seq_len,
    )
    params, opt_state, step_fn, put_batch = make_lm_train_step(config, mesh, lr)

    rng = np.random.default_rng(0)
    data = rng.integers(0, vocab, size=(batch, seq_len + 1), dtype=np.int32)
    profile = ctx is not None and assignments.get("profile", "0") == "1"
    import contextlib

    prof_cm = ctx.profile() if profile else contextlib.nullcontext()
    # the synthetic batch is constant across steps: stage it once
    tokens, targets, positions = put_batch(data[:, :-1], data[:, 1:])
    with prof_cm:
        for i in range(steps):
            params, opt_state, loss = step_fn(params, opt_state, tokens, targets, positions)
            if ctx is not None and (i + 1) % 5 == 0:
                ctx.report(loss=float(loss))
    if ctx is not None:
        if steps % 5 != 0:  # final value not yet reported by the loop
            ctx.report(loss=float(loss))
    else:
        print(f"loss={float(loss)}")


# semantic-analysis probe (katib_tpu.analysis.program): the abstract twin of
# this trial's train step lives next to the model it shapes
from ..models.transformer import abstract_lm_program  # noqa: E402

run_lm_trial.abstract_program = abstract_lm_program
