"""Pipeline-parallel LM training: GPipe microbatch schedule over the mesh
'pipe' axis, built from XLA collectives inside one jitted step.

The reference has no pipeline engine of its own — distributed trials delegate
to PyTorchJob/MPIJob images (SURVEY.md §2.9). The TPU-native equivalent is an
SPMD rotational pipeline (the scaling-book construction): transformer blocks
are stacked [n_stages, layers_per_stage, ...] and sharded over 'pipe'; inside
``jax.shard_map`` each device applies its stage and hands activations to the
next stage with ``lax.ppermute`` while stage 0 feeds in a fresh microbatch —
so after the (n_stages−1)-step bubble every stage computes concurrently.
The backward pipeline falls out of autodiff: ppermute's transpose is the
reverse rotation, so jax.grad of the scanned forward IS the reverse schedule.

Gradient reductions are explicit (pmap-style manual collectives): stage
params take no cross-'pipe' reduction (each device owns its stage), shared
params (embedding, final norm) psum over 'pipe', and everything pmeans over
'data'.

Embedding and the tied LM head live outside the rotation (computed on every
pipe device; only stage 0's embedding and the last stage's head carry
gradients — masking in the schedule routes cotangents correctly).

Tensor parallelism and ZeRO compose INSIDE each stage: the shard_map is
manual over 'pipe' and 'data' only (``axis_names``), leaving 'model' and
'fsdp' automatic GSPMD axes — stage weights carry the TP + fsdp shardings
from ``transformer.param_sharding_rules`` (each stage's weights and
optimizer state are additionally sharded over 'fsdp', gathered at compute,
grads reduce-scattered back) and XLA inserts the within-stage collectives
while the rotation stays a manual ppermute over 'pipe'. This is the
standard pp x fsdp x tp x dp TPU layout: TP on the innermost (fastest-ICI)
axis, pipeline and data outermost.

Sequence parallelism also composes INSIDE each stage: with mesh axis
``seq > 1`` the shard_map goes manual over 'seq' as well, tokens and
activations carry T/seq_par-length shards, and each stage's attention runs
the ring schedule (``Attention.seq_axis`` → ``ring_attention_local``) over
the axis — long-context training through a pipeline.

Expert parallelism composes the same way: with ``expert > 1`` the axis is a
manual batch axis outside the MoE layers (extra data parallelism), each
device's stage holds num_experts/expert_par expert FFNs
(``MoE.expert_axis``), one tiled all_to_all per direction exchanges
batch-shards for expert-shards inside the layer, and per-stage
load-balance aux losses (computed per shard — the standard per-device MoE
aux treatment) fold into the pipeline loss via the 'pipe' psum, masked to
the steps where the stage held a real microbatch.

Constraints: batch divisible by n_microbatches × data-axis size (× the
expert-axis size when expert > 1); T divisible by the seq-axis size;
num_experts divisible by the expert-axis size; positions are arange(T)
offset by the seq rank (identical across microbatches, so RoPE state
doesn't need to travel with activations).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.transformer import Block, RMSNorm, TransformerConfig, collect_moe_aux
from .mesh import mesh_axis_sizes


def _stack_block_init(config: TransformerConfig, n_stages: int, layers_per_stage: int, seed: int):
    """Init num_layers independent blocks, stacked to [n_stages, lps, ...]."""
    block = Block(config, mesh=None)
    sample_x = jnp.zeros((1, 8, config.embed_dim), config.dtype)
    sample_pos = jnp.zeros((1, 8), jnp.int32)
    n = n_stages * layers_per_stage
    rngs = jax.random.split(jax.random.PRNGKey(seed), n)

    def init_one(rng):
        return block.init(rng, sample_x, sample_pos)["params"]

    stacked = jax.vmap(init_one)(rngs)
    return jax.tree.map(
        lambda a: a.reshape((n_stages, layers_per_stage) + a.shape[1:]), stacked
    )


def make_pipeline_lm_train_step(
    config: TransformerConfig,
    mesh,
    learning_rate: float = 1e-3,
    num_microbatches: Optional[int] = None,
    seed: int = 0,
    tx=None,
):
    """Returns (params, opt_state, step_fn, put_batch) with
    step_fn(params, opt_state, tokens, targets) -> (params, opt_state, loss)
    and put_batch(tokens, targets) placing host arrays with the step's
    expected NamedSharding.

    tokens/targets: [B, T] int32, B sharded over 'data'. params is
    {'embed': [V, E], 'blocks': pytree with leading [n_stages, lps],
    'ln_f': [E]} with blocks sharded over 'pipe'.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    sizes = mesh_axis_sizes(mesh)
    n_stages = sizes.get("pipe", 1)
    if n_stages < 2:
        raise ValueError("pipeline path needs mesh axis 'pipe' >= 2")
    if config.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers {config.num_layers} not divisible by pipe={n_stages}"
        )
    lps = config.num_layers // n_stages
    n_micro = num_microbatches or 2 * n_stages
    # sequence parallelism inside each stage: the shard_map goes manual over
    # 'seq' too, activations carry T/seq_par tokens, and the stage's
    # attention runs the ring schedule (Attention.seq_axis) directly over
    # the axis — long context composes with the pipeline
    seq_par = sizes.get("seq", 1)
    # expert parallelism inside each stage: 'expert' is a manual batch axis
    # outside the MoE layers (extra DP) and the MoE exchanges tokens for
    # experts with a direct all_to_all over it (MoE.expert_axis); each
    # device's stage holds num_experts/expert_par expert FFNs
    expert_par = sizes.get("expert", 1)
    moe_in_stage = expert_par > 1 and config.num_experts > 0
    if moe_in_stage and config.num_experts % expert_par != 0:
        raise ValueError(
            f"num_experts {config.num_experts} not divisible by "
            f"expert={expert_par}"
        )

    block = Block(
        config, mesh=None,
        seq_axis="seq" if seq_par > 1 else None,
        expert_axis="expert" if moe_in_stage else None,
        expert_axis_size=expert_par if moe_in_stage else 1,
    )

    embed = jax.random.normal(
        jax.random.PRNGKey(seed + 1), (config.vocab_size, config.embed_dim), jnp.float32
    ) * 0.02
    blocks = _stack_block_init(config, n_stages, lps, seed)
    # Stage weights: 'pipe' on the stage dim (manual), the block's TP + ZeRO
    # rules on the trailing dims ('model' and 'fsdp' are auto/GSPMD axes
    # inside the shard_map: TP splits the matmuls, fsdp shards storage and
    # gathers at compute).
    import flax

    from ..models.transformer import param_sharding_rules

    flat_blocks = flax.traverse_util.flatten_dict(blocks)
    sharded_blocks = {
        k: jax.device_put(
            v,
            NamedSharding(mesh, P("pipe", None, *tuple(param_sharding_rules(k)))),
        )
        for k, v in flat_blocks.items()
    }
    params = {
        "embed": jax.device_put(embed, NamedSharding(mesh, P(None, None))),
        "blocks": flax.traverse_util.unflatten_dict(sharded_blocks),
        "ln_f": jax.device_put(jnp.ones((config.embed_dim,)), NamedSharding(mesh, P(None))),
    }

    tx = tx or optax.adamw(learning_rate, weight_decay=0.01)
    opt_state = tx.init(params)

    def stage_apply(blocks_local, x, positions):
        # blocks_local leaves [1, lps, ...]; scan over the stage's layers.
        # MoE stages also surface the sown load-balance aux loss (computed
        # per shard — the mean over shards approximates the global statistic,
        # the standard per-device MoE aux treatment).
        layer_params = jax.tree.map(lambda a: a[0], blocks_local)

        def one(carry, p):
            if config.num_experts > 0:
                y, mut = block.apply(
                    {"params": p}, carry, positions, mutable=["intermediates"]
                )
                return y, collect_moe_aux(mut)
            return block.apply({"params": p}, carry, positions), jnp.float32(0.0)

        x, auxs = jax.lax.scan(one, x, layer_params)
        return x, jnp.sum(auxs)

    def device_loss(embed_p, blocks_local, lnf, tokens, targets):
        # tokens/targets: [B_local, T_local] (T sharded over 'seq' when
        # seq_par > 1 — positions must be GLOBAL for RoPE and causality)
        b, t = tokens.shape
        mb = b // n_micro
        stage = jax.lax.axis_index("pipe")
        t_off = jax.lax.axis_index("seq") * t if seq_par > 1 else 0
        positions = jnp.broadcast_to(
            t_off + jnp.arange(t, dtype=jnp.int32), (mb, t)
        )

        x = embed_p[tokens].astype(config.dtype).reshape(n_micro, mb, t, -1)
        tgt = targets.reshape(n_micro, mb, t)

        def body(carry, step_i):
            state, out_buf, aux_tot = carry
            shifted = jax.lax.ppermute(
                state, "pipe", [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            inp = jnp.where(
                step_i < n_micro,
                jax.lax.dynamic_index_in_dim(
                    x, jnp.minimum(step_i, n_micro - 1), 0, keepdims=False
                ),
                jnp.zeros_like(x[0]),
            )
            x_in = jnp.where(stage == 0, inp, shifted)
            y, aux = stage_apply(blocks_local, x_in, positions)
            # aux only counts while this stage holds a REAL microbatch —
            # bubble steps run on zero activations and would pollute it
            valid = (step_i >= stage) & (step_i < stage + n_micro)
            aux_tot = aux_tot + jnp.where(valid, aux, 0.0)
            widx = jnp.clip(step_i - (n_stages - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, widx, 0, keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(step_i >= n_stages - 1, y, cur), widx, 0
            )
            return (y, out_buf, aux_tot), None

        state0 = jnp.zeros_like(x[0])
        out_buf0 = jnp.zeros_like(x)
        (_, out_buf, aux_tot), _ = jax.lax.scan(
            body, (state0, out_buf0, jnp.float32(0.0)),
            jnp.arange(n_micro + n_stages - 1),
        )

        # Head + loss. SPMD means every stage executes this code (a
        # device-varying lax.cond would lower to a select that still runs
        # both branches), but scanning over microbatches keeps the logits
        # buffer at [mb, T, V] instead of materializing [n_micro, mb, T, V]
        # vocab logits on every device; only the last stage's value is kept.
        h = RMSNorm().apply({"params": {"scale": lnf}}, out_buf)

        def ce_micro(acc, hm_tm):
            hm, tm = hm_tm
            logits = jnp.einsum("bte,ve->btv", hm.astype(jnp.float32), embed_p)
            return acc + optax.softmax_cross_entropy_with_integer_labels(logits, tm).mean(), None

        local, _ = jax.lax.scan(ce_micro, jnp.float32(0.0), (h, tgt))
        masked = jnp.where(stage == n_stages - 1, local / n_micro, 0.0)
        # CE lives on the last stage; every stage contributes its own MoE
        # aux (per-microbatch average) — one psum folds both across 'pipe'
        return jax.lax.psum(masked + aux_tot / n_micro, "pipe")

    def _allmean(g, expert_sharded=False):
        # Parameter gradient vs the MEAN loss over all shards: the ring
        # ppermute / a2a transposes have already routed cross-shard
        # cotangents, so each rank holds d(sum of the losses it fed)/d(its
        # copy). Replicated params average over every batch-like axis
        # (data, seq, expert). Expert-SHARDED leaves exist once per expert
        # group — no expert mean — but their per-rank grad already sums the
        # expert_par device losses of their data rank, so it must be scaled
        # by 1/expert_par to match the (1/(D·E))·Σ mean-loss gradient that
        # every other parameter gets.
        g = jax.lax.pmean(g, "data")
        if seq_par > 1:
            g = jax.lax.pmean(g, "seq")
        if expert_par > 1:
            g = g / expert_par if expert_sharded else jax.lax.pmean(g, "expert")
        return g

    # Per-leaf manual specs and an expert-sharded mask: MoE FFN weights are
    # MANUAL-sharded over 'expert' (each device owns distinct experts), so
    # their grads must NOT be averaged over the expert axis — every other
    # block param is replicated across it and must be.
    spec_map, exp_map = {}, {}
    for k in flax.traverse_util.flatten_dict(params["blocks"]):
        rules = tuple(param_sharding_rules(k))
        spec_map[k] = P("pipe", None, *(
            ("expert" if (moe_in_stage and r == "expert") else None)
            for r in rules
        ))
        exp_map[k] = moe_in_stage and ("expert" in rules)
    blocks_spec = flax.traverse_util.unflatten_dict(spec_map)
    blocks_expert_sharded = flax.traverse_util.unflatten_dict(exp_map)

    def spmd_step(embed_p, blocks_local, lnf, tokens, targets):
        loss, grads = jax.value_and_grad(device_loss, argnums=(0, 1, 2))(
            embed_p, blocks_local, lnf, tokens, targets
        )
        g_embed, g_blocks, g_lnf = grads
        g_embed = _allmean(jax.lax.psum(g_embed, "pipe"))
        g_lnf = _allmean(jax.lax.psum(g_lnf, "pipe"))
        g_blocks = jax.tree.map(_allmean, g_blocks, blocks_expert_sharded)
        loss = _allmean(loss)
        return loss, g_embed, g_blocks, g_lnf

    # Manual over pipe+data (+seq/+expert with in-stage SP/EP): 'model' and
    # 'fsdp' stay automatic, so the TP/ZeRO shardings on the stage weights
    # make XLA insert the within-stage collectives while the rotation stays
    # a manual ppermute over 'pipe', attention rings over 'seq', and the
    # MoE all_to_all rides 'expert'.
    batch_axes = ("data", "expert") if expert_par > 1 else "data"
    token_spec = P(batch_axes, "seq" if seq_par > 1 else None)
    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(P(None, None), blocks_spec, P(None), token_spec, token_spec),
        out_specs=(P(), P(None, None), blocks_spec, P(None)),
        check_vma=False,
        axis_names=(
            {"pipe", "data"}
            | ({"seq"} if seq_par > 1 else set())
            | ({"expert"} if expert_par > 1 else set())
        ),
    )

    def step(params, opt_state, tokens, targets):
        loss, g_embed, g_blocks, g_lnf = sharded(
            params["embed"], params["blocks"], params["ln_f"], tokens, targets
        )
        grads = {"embed": g_embed, "blocks": g_blocks, "ln_f": g_lnf}
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step_fn = jax.jit(step, donate_argnums=(0, 1))

    batch_sharding = NamedSharding(mesh, token_spec)

    def put_batch(tokens, targets):
        return (
            jax.device_put(tokens, batch_sharding),
            jax.device_put(targets, batch_sharding),
        )

    return params, opt_state, step_fn, put_batch
