"""Device mesh construction + multi-host initialization.

The reference delegates distributed training to per-trial K8s CRDs (PyTorchJob
DDP / MPIJob Horovod — SURVEY.md §2.9); the TPU-native equivalent is a named
``jax.sharding.Mesh`` over the trial's gang-allocated chips with XLA
collectives over ICI within a slice and DCN across slices.

Axis convention (the scaling-book recipe):
- ``data``  — batch sharding (DP); gradients all-reduce (psum) over ICI
- ``fsdp``  — parameter/optimizer sharding over the data axis (ZeRO-style)
- ``model`` — tensor parallelism (TP); activations all-gather / reduce-scatter
- ``seq``   — sequence/context parallelism (ring attention over ppermute)
- ``expert``— expert parallelism for MoE layers
- ``pipe``  — pipeline stages
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "model")


def distributed_initialized() -> bool:
    """Is the jax.distributed client up? ``jax.distributed.is_initialized``
    only exists on newer jax; older versions keep the state object in
    ``jax._src.distributed`` — probe both rather than crash on a version
    mismatch. Inspects only the distributed client, never the XLA backend."""
    import jax

    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up — jax.distributed.initialize on TPU-VM workers.

    Replaces the reference's dependence on the training-operator to wire
    MASTER_ADDR/RANK into PyTorchJob pods: here the trial runtime calls this
    on every host of the slice (no-op when single-process or when JAX already
    auto-detects TPU pod topology).
    """
    import jax

    # NOTE: must not touch jax.process_count()/jax.devices() here — those
    # initialize the XLA backend, after which jax.distributed.initialize()
    # refuses to run. distributed_initialized() inspects only the client.
    if distributed_initialized():
        return
    addr = coordinator_address or os.environ.get("KATIB_TPU_COORDINATOR")
    nproc = num_processes or int(os.environ.get("KATIB_TPU_NUM_PROCESSES", "0"))
    pid = process_id if process_id is not None else int(os.environ.get("KATIB_TPU_PROCESS_ID", "0"))
    if addr and nproc > 1:
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=nproc, process_id=pid
        )


def make_mesh(
    devices: Optional[Sequence[Any]] = None,
    *,
    data: int = -1,
    fsdp: int = 1,
    model: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
):
    """Build a named Mesh; ``data=-1`` absorbs the remaining devices.

    Axis order puts ``model`` (highest-bandwidth collectives) innermost so TP
    rides the fastest ICI links, and ``pipe``/``data`` outermost (DCN-friendly)
    — the standard TPU layout.
    """
    from jax.sharding import Mesh

    if devices is None:
        from ..utils.backend import require_devices

        # bounded probe with cached verdict (utils/backend.py): mesh
        # construction on a wedged backend raises fast instead of blocking
        # the caller for minutes (KTI304)
        devices = require_devices()
    n = len(devices)
    sizes = {"pipe": pipe, "data": data, "fsdp": fsdp, "expert": expert, "seq": seq, "model": model}
    fixed = 1
    for name, s in sizes.items():
        if s != -1:
            fixed *= s
    if sizes["data"] == -1:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes product {fixed}")
        sizes["data"] = n // fixed
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(f"mesh {sizes} needs {total} devices, got {n}")
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def mesh_axis_sizes(mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


ACTIVATION_BATCH_AXES = ("data", "fsdp", "expert")


def activation_batch_axes(
    mesh_or_sizes, batch: int, axes: Sequence[str] = ACTIVATION_BATCH_AXES
) -> Tuple[str, ...]:
    """Greedy batch-sharding axes for activations: shard over each of
    data/fsdp/expert in order while ``batch`` divides the running product.

    'expert' acts as pure extra data parallelism OUTSIDE the MoE layers —
    attention and norms never compute redundantly across the expert axis —
    and the MoE dispatch einsum's sharding constraint re-splits tokens
    expert-wise with one all-to-all at the layer boundary (the scaling-book
    EP recipe)."""
    sizes = (
        mesh_or_sizes
        if isinstance(mesh_or_sizes, dict)
        else mesh_axis_sizes(mesh_or_sizes)
    )
    out: List[str] = []
    prod = 1
    for a in axes:
        s = sizes.get(a, 1)
        if s > 1 and batch % (prod * s) == 0:
            out.append(a)
            prod *= s
    return tuple(out)


def batch_spec(batch: Optional[int] = None, mesh=None):
    """Canonical activation sharding: batch over data+fsdp+expert, sequence
    over seq. With ``batch`` and ``mesh`` given, the batch axes are trimmed
    to what the batch size actually divides."""
    from jax.sharding import PartitionSpec as P

    if batch is None or mesh is None:
        return P(ACTIVATION_BATCH_AXES, "seq")
    return P(activation_batch_axes(mesh, batch) or None, "seq")
