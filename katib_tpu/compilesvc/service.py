"""CompileService — the controller-side compilation plane.

Compilation used to be a surprise tax inside the first trial's stint: the
executor called the trial function, the function hit ``jax.jit``, and the
gang's chips idled for the 23–51s XLA compile BENCH_r02/r04 measured. This
service makes compilation a *scheduled, cached, observable* resource:

- **Admission-time AOT compile.** When a trial is submitted (and already at
  ``create_experiment`` via :meth:`CompileService.prewarm`), its dispatch
  group's PR 7 :class:`~katib_tpu.analysis.program.ProgramProbe` is queued
  for an ahead-of-time ``jit(fn).trace(*avals).lower().compile()`` on a
  small worker pool — off the dispatch path, so chips never wait on XLA
  when the gate is on. One ``.trace`` serves both the compile fingerprint
  (byte-identical to the analysis fingerprint — same canonical jaxpr) and
  the lowering, so the shared program of an N-trial runtime-scalar sweep is
  traced exactly once in the service.
- **Fingerprint-keyed executable registry.** Entries progress
  ``pending → compiling → warm`` (or ``failed``); the registry is keyed by
  the dispatch-group key on the request path (a dict hit under the
  scheduler's walk) and deduplicated by compile fingerprint across groups
  — two templates lowering to the same program share one executable.
- **Failure quarantine.** A failed compile emits exactly one
  ``CompileFailed`` warning event and the fingerprint is quarantined: it is
  never recompiled per trial; trials fall back to inline compilation in the
  executor (where the real exception surfaces per trial as before).
- **Cost-ordered queue.** Jobs are ordered by the PR 7 cost model's FLOPs,
  biggest first, so the longest compile starts earliest.
- **Timeout + worker-crash isolation.** Each compile runs on an inner
  daemon thread with a per-compile timeout; a wedged XLA (or a crashing
  probe) fails that entry, never the worker pool or the controller.
- **Warm handoff.** In-process trials receive the compiled executable via
  ``ctx.compiled_program`` (scheduler → TrialContext); subprocess and gang
  trials get their warmth via the shared persistent XLA cache
  (utils/compilation.py), which the service's AOT compiles pre-populate.

Observability: ``katib_compile_queue_depth``, ``katib_compile_cache_hit_-
total``/``miss_total``, ``katib_compile_failed_total`` and the
``katib_compile_seconds`` histogram; a ``compile_service`` span joined to
the first requesting trial's trace; ``katib-tpu compile [--url]`` renders
the registry (live via ``/api/compile`` or from the JSON snapshot persisted
under ``<root>/compilesvc/``).

Disabled (``runtime.compile_service=false`` / ``KATIB_TPU_COMPILE_SERVICE=0``)
the controller never constructs the service and every scheduler/packing/
context consult is one ``is None`` check — dispatch is byte-identical to the
legacy path.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger("katib_tpu.compilesvc")

STATE_PENDING = "pending"
STATE_COMPILING = "compiling"
STATE_WARM = "warm"
STATE_FAILED = "failed"

QUEUE_DEPTH_METRIC = "katib_compile_queue_depth"
HIT_METRIC = "katib_compile_cache_hit_total"
MISS_METRIC = "katib_compile_cache_miss_total"
FAILED_METRIC = "katib_compile_failed_total"
SECONDS_METRIC = "katib_compile_seconds"

REGISTRY_FILE = "registry.json"

# Process-level executable cache, keyed by compile fingerprint — the
# service-side analogue of the jit cache. Fingerprints are process-stable
# and include donation/statics, so two CompileService instances (repeat
# experiments, multiple controllers, test suites) tracing the same program
# share one executable instead of recompiling it. Bounded; oldest evicted.
_PROCESS_CACHE_MAX = 64
_PROCESS_CACHE: "collections.OrderedDict[str, Tuple[Any, float]]" = (
    collections.OrderedDict()
)
_PROCESS_CACHE_LOCK = threading.Lock()


def clear_process_cache() -> None:
    """Drop the process-level executable cache (test isolation hook)."""
    with _PROCESS_CACHE_LOCK:
        _PROCESS_CACHE.clear()


def _process_cache_get(fingerprint: str):
    with _PROCESS_CACHE_LOCK:
        hit = _PROCESS_CACHE.get(fingerprint)
        if hit is not None:
            _PROCESS_CACHE.move_to_end(fingerprint)
        return hit


def _process_cache_put(fingerprint: str, executable, compile_seconds: float) -> None:
    with _PROCESS_CACHE_LOCK:
        _PROCESS_CACHE[fingerprint] = (executable, compile_seconds)
        _PROCESS_CACHE.move_to_end(fingerprint)
        while len(_PROCESS_CACHE) > _PROCESS_CACHE_MAX:
            _PROCESS_CACHE.popitem(last=False)


@dataclass
class WarmProgram:
    """Handle the scheduler passes to an in-process trial via
    ``ctx.compiled_program``: the AOT-compiled executable for the trial's
    dispatch group plus enough metadata to sanity-check it. ``executable``
    is a ``jax.stages.Compiled`` — call it with concrete arrays matching
    the probe's avals."""

    fingerprint: str
    executable: Any
    target: str
    compile_seconds: float


@dataclass
class CompileEntry:
    """One dispatch group's slot in the registry."""

    key: Any                      # dispatch-group key (analysis/program.py)
    experiment: str               # first requesting experiment
    target: str                   # "module:fn" of the probed entry point
    state: str = STATE_PENDING
    fingerprint: str = ""         # filled by the worker's trace
    cost_flops: float = 0.0       # PR 7 cost model (queue priority)
    submitted_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    compiled_at: Optional[float] = None
    compile_seconds: Optional[float] = None
    trials_served: int = 0        # requests answered for this group
    prewarmed: bool = False       # enqueued at admission, before any trial
    error: Optional[str] = None
    executable: Any = None        # in-memory only, never serialized
    # (trace_id, parent_span_id) of the first requesting trial's root span;
    # prewarm entries start without one and adopt the first trial's trace,
    # so the compile_service span joins a real trial trace when possible
    trace: Optional[Tuple[str, str]] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "key": _key_str(self.key),
            "experiment": self.experiment,
            "target": self.target,
            "state": self.state,
            "fingerprint": self.fingerprint,
            "costFlops": self.cost_flops,
            "submittedAt": self.submitted_at,
            "startedAt": self.started_at,
            "compiledAt": self.compiled_at,
            "compileSeconds": self.compile_seconds,
            "trialsServed": self.trials_served,
            "prewarmed": self.prewarmed,
            "error": self.error,
            "hasExecutable": self.executable is not None,
        }


def _key_str(key: Any) -> str:
    """Stable human-readable form of a dispatch-group key:
    ``<digest>[name=value,...]``."""
    try:
        digest, values = key
        inner = ",".join(f"{n}={v}" for n, v in values)
        return f"{digest}[{inner}]"
    except Exception:
        return repr(key)


@dataclass
class _Job:
    """One queued compile: everything the worker needs, detached from the
    live Experiment/Trial objects so the queue holds no control-plane
    state."""

    key: Any
    experiment: str
    target: str
    builder: Callable[[Dict[str, str]], Any]   # fn.abstract_program
    assignments: Dict[str, str]
    cost_flops: float


class CompileService:
    """Controller-owned AOT compiler with a fingerprint-keyed registry.

    Thread model: ``request``/``prewarm`` run on control-plane threads
    (submit path, create_experiment); ``state_for_key``/``is_warm``/
    ``warm_executable_for`` run under the scheduler's dispatch lock (they
    take only this service's lock — the scheduler→service lock order is the
    one direction ever used); workers notify listeners *outside* the
    service lock, so a listener re-entering the scheduler cannot form a
    lock-order cycle (verified by the lockgraph stress test).
    """

    # executables kept resident for in-process handoff; metadata is never
    # evicted (the registry is the observability surface), only the
    # executables of the oldest warm entries beyond this cap are dropped —
    # those groups still benefit from the persistent XLA cache
    MAX_RESIDENT_EXECUTABLES = 64

    def __init__(
        self,
        workers: int = 2,
        timeout_seconds: float = 600.0,
        metrics=None,
        events=None,
        tracer=None,
        persist_dir: Optional[str] = None,
    ):
        self.workers = max(int(workers), 1)
        self.timeout_seconds = timeout_seconds
        self.metrics = metrics
        self.events = events
        self.tracer = tracer
        self.persist_dir = persist_dir
        self._lock = threading.Lock()
        self._by_key: Dict[Any, CompileEntry] = {}
        self._by_fingerprint: Dict[str, CompileEntry] = {}
        self._warm_order: List[str] = []  # fingerprints, oldest first
        self._queue: "queue.PriorityQueue" = queue.PriorityQueue()
        self._seq = 0
        self._threads: List[threading.Thread] = []
        self._listeners: List[Callable[[Any], None]] = []
        self._running = False
        # counters surfaced by stats(): every executed compile bumps
        # trace_counter exactly once — the acceptance sweep's assertion that
        # a shared program is traced once *in the service*
        self.trace_counter = 0
        self.compiled_total = 0
        self.hits = 0
        self.misses = 0
        self._cache_enabled = False

    # -- lifecycle -----------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._running

    def start(self) -> None:
        """Spawn the worker pool (idempotent)."""
        with self._lock:
            if self._running:
                return
            self._running = True
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker_loop, daemon=True, name=f"compile-worker-{i}"
            )
            with self._lock:
                self._threads.append(t)
            t.start()

    def stop(self) -> None:
        """Stop the pool. In-flight compiles finish on their inner daemon
        threads and are discarded; queued jobs are dropped."""
        with self._lock:
            if not self._running:
                return
            self._running = False
            threads = list(self._threads)
            self._threads = []
        for _ in threads:
            self._queue.put((float("inf"), self._next_seq(), None))  # sentinel
        for t in threads:
            t.join(timeout=2.0)
        # final snapshot: request counters (hits/trialsServed) accrued since
        # the last compile transition reach the offline `katib-tpu compile`
        self._persist()

    def add_listener(self, fn: Callable[[Any], None]) -> None:
        """Register a state-transition hook ``fn(group_key)`` — the
        scheduler re-runs its dispatch pass when a program turns warm (or
        fails, releasing any gate hold). Called from worker threads with NO
        service lock held."""
        with self._lock:
            self._listeners.append(fn)

    # -- request path (control-plane threads) --------------------------------

    def request(self, exp, trial, trace: Optional[Tuple[str, str]] = None) -> Optional[Any]:
        """Ask for the trial's dispatch group to be warm. Returns the group
        key (None when the template is unanalyzable — command templates,
        probe-less functions, analysis off). Dict hit after the first
        request per group; a ``failed`` entry is quarantined and never
        re-enqueued."""
        if not self._running:
            return None
        from ..analysis import program as semantic

        try:
            key = semantic.dispatch_group_key(exp.spec, trial)
        except Exception:
            key = None
        if key is None:
            return None
        # resolve the probe/analysis OUTSIDE the service lock: the analysis
        # cache is warm here (dispatch_group_key above consulted it), but a
        # cold cache must never hold this lock through a trace — the
        # scheduler's dispatch walk consults state_for_key under its own lock
        admission = self._resolve_admission(exp.spec)
        job = None
        with self._lock:
            entry = self._by_key.get(key)
            if entry is not None:
                entry.trials_served += 1
                if entry.trace is None and trace is not None:
                    entry.trace = trace  # adopt the first trial's trace
                hit = entry.state == STATE_WARM
            else:
                hit = False
                entry, job = self._admit_locked(
                    key, exp.spec.name, dict(trial.assignments_dict()), trace,
                    admission,
                )
                if entry is not None:
                    entry.trials_served = 1
        self._count_request(exp.name, hit)
        if job is not None:
            self._enqueue(job)
        return key

    def prewarm(self, spec) -> Optional[Any]:
        """Admission-time warm-up: enqueue the spec's *baseline* dispatch
        group before any trial exists, so the first suggestion batch of a
        runtime-scalar sweep already finds its executable compiling (or
        warm). Returns the group key or None."""
        if not self._running:
            return None
        from ..analysis import program as semantic

        try:
            analysis = semantic.cached_analysis(spec)
            if analysis is None or not analysis.analyzable:
                return None
            baseline = semantic.baseline_assignments(spec)
            key = semantic.dispatch_group_key_for_assignments(spec, baseline)
        except Exception:
            return None
        if key is None:
            return None
        admission = self._resolve_admission(spec)
        job = None
        with self._lock:
            entry = self._by_key.get(key)
            if entry is None:
                entry, job = self._admit_locked(
                    key, spec.name, dict(baseline), None, admission
                )
                if entry is not None:
                    entry.prewarmed = True
        if job is not None:
            self._enqueue(job)
        return key

    def request_group(
        self,
        key: Any,
        experiment: str,
        target: str,
        builder: Callable[[Dict[str, str]], Any],
        assignments: Optional[Dict[str, str]] = None,
        cost_flops: float = 0.0,
        trace: Optional[Tuple[str, str]] = None,
    ) -> Optional[Any]:
        """Generic group admission — the fused population runtime (and any
        future non-per-trial program source) registers its program under an
        explicit registry key with its own ProgramProbe builder. Same
        lifecycle as a per-trial dispatch group: pending → compiling →
        warm/failed, fingerprint-deduplicated, cost-ordered, quarantined on
        failure. Returns the key (None when the service is stopped)."""
        if not self._running:
            return None
        job = None
        with self._lock:
            entry = self._by_key.get(key)
            if entry is not None:
                entry.trials_served += 1
                if entry.trace is None and trace is not None:
                    entry.trace = trace
                hit = entry.state == STATE_WARM
            else:
                hit = False
                entry, job = self._admit_locked(
                    key, experiment, dict(assignments or {}), trace,
                    (builder, target, float(cost_flops)),
                )
                if entry is not None:
                    entry.trials_served = 1
        self._count_request(experiment, hit)
        if job is not None:
            self._enqueue(job)
        return key

    def warm_executable_for_key(self, key: Any) -> Optional[WarmProgram]:
        """The compiled executable for an explicit registry key, when warm
        and still resident — the request_group counterpart of
        ``warm_executable_for``."""
        if key is None:
            return None
        with self._lock:
            entry = self._by_key.get(key)
            if (
                entry is None
                or entry.state != STATE_WARM
                or entry.executable is None
            ):
                return None
            return WarmProgram(
                fingerprint=entry.fingerprint,
                executable=entry.executable,
                target=entry.target,
                compile_seconds=entry.compile_seconds or 0.0,
            )

    @staticmethod
    def _resolve_admission(spec) -> Optional[Tuple[Callable, str, float]]:
        """(probe builder, target name, cost FLOPs) for a spec, or None when
        it has no probe. Runs lock-free — the analysis cache consult may
        trace on a cold cache."""
        from ..analysis import program as semantic

        builder = semantic.probe_builder_for(spec.trial_template)
        if builder is None:
            return None
        analysis = semantic.cached_analysis(spec)
        target = analysis.target if analysis is not None else "?"
        cost = 0.0
        if analysis is not None and analysis.cost is not None:
            cost = float(analysis.cost.flops)
        return builder, target, cost

    def _admit_locked(
        self, key, experiment: str, assignments: Dict[str, str], trace, admission
    ) -> Tuple[Optional[CompileEntry], Optional[_Job]]:
        """Create the registry entry + job for a new group. Caller holds the
        service lock; ``admission`` was resolved outside it."""
        if admission is None:
            return None, None
        builder, target, cost = admission
        entry = CompileEntry(
            key=key, experiment=experiment, target=target, cost_flops=cost,
            trace=trace,
        )
        self._by_key[key] = entry
        job = _Job(
            key=key,
            experiment=experiment,
            target=target,
            builder=builder,
            assignments=assignments,
            cost_flops=cost,
        )
        return entry, job

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _enqueue(self, job: _Job) -> None:
        # cost-ordered: biggest program first (longest compile starts
        # earliest); seq breaks ties in arrival order
        self._queue.put((-job.cost_flops, self._next_seq(), job))
        self._set_queue_gauge()

    def _count_request(self, experiment: str, hit: bool) -> None:
        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
        if self.metrics is not None:
            self.metrics.inc(
                HIT_METRIC if hit else MISS_METRIC, experiment=experiment
            )

    def _set_queue_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge(QUEUE_DEPTH_METRIC, float(self._queue.qsize()))

    # -- consult path (scheduler dispatch lock) ------------------------------

    def state_for_key(self, key) -> Optional[str]:
        """Registry state for one dispatch-group key (dict hit; None =
        unknown group)."""
        if key is None:
            return None
        with self._lock:
            entry = self._by_key.get(key)
            return entry.state if entry is not None else None

    def is_warm(self, spec, trial) -> bool:
        """Warm-executable predicate for dispatch ordering / pack
        preference."""
        from ..analysis import program as semantic

        try:
            key = semantic.dispatch_group_key(spec, trial)
        except Exception:
            return False
        return self.state_for_key(key) == STATE_WARM

    def warm_executable_for(self, spec, trial) -> Optional[WarmProgram]:
        """The compiled executable for this trial's group, when warm and
        still resident — handed to in-process trials via
        ``ctx.compiled_program``."""
        from ..analysis import program as semantic

        try:
            key = semantic.dispatch_group_key(spec, trial)
        except Exception:
            return None
        if key is None:
            return None
        with self._lock:
            entry = self._by_key.get(key)
            if (
                entry is None
                or entry.state != STATE_WARM
                or entry.executable is None
            ):
                return None
            return WarmProgram(
                fingerprint=entry.fingerprint,
                executable=entry.executable,
                target=entry.target,
                compile_seconds=entry.compile_seconds or 0.0,
            )

    # -- worker pool ---------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            _, _, job = self._queue.get()
            self._set_queue_gauge()
            if job is None:  # stop sentinel
                return
            if not self._running:
                return
            try:
                self._run_job(job)
            except Exception:
                # worker-crash isolation: a bug in the job plumbing fails
                # that job's entry (below, via _fail) or at worst logs —
                # the pool itself never dies
                log.exception("compile job for %s crashed", job.target)

    def _run_job(self, job: _Job) -> None:
        with self._lock:
            entry = self._by_key.get(job.key)
            if entry is None or entry.state != STATE_PENDING:
                return  # raced with stop/duplicate; nothing to do
            entry.state = STATE_COMPILING
            entry.started_at = time.time()
            trace_ctx = entry.trace
        span = None
        tracer = self.tracer
        if tracer is not None and getattr(tracer, "enabled", False) and trace_ctx:
            trace_id, parent_id = trace_ctx
            span = tracer.start_span(
                "compile_service", job.experiment, trace_id, parent_id,
                attrs={"target": job.target, "costFlops": job.cost_flops},
            )
        box: Dict[str, Any] = {}

        def _do():
            try:
                box["result"] = self._compile_probe(job)
            except BaseException:
                box["error"] = traceback.format_exc(limit=10)

        inner = threading.Thread(
            target=_do, daemon=True, name=f"compile-{job.target}"
        )
        started = time.time()
        inner.start()
        inner.join(self.timeout_seconds)
        if inner.is_alive():
            # wedged XLA / backend init: abandon the inner thread (it is a
            # daemon), quarantine the fingerprint — per-compile timeout is
            # the worker-crash isolation boundary
            self._fail(
                job,
                f"compile exceeded {self.timeout_seconds:.0f}s; "
                "abandoned (fingerprint quarantined)",
            )
            if span is not None:
                tracer.end_span(span, outcome="timeout")
            return
        if "error" in box:
            self._fail(job, box["error"])
            if span is not None:
                tracer.end_span(span, outcome="failed")
            return
        fingerprint, executable, reused = box["result"]
        elapsed = time.time() - started
        if not reused:
            _process_cache_put(fingerprint, executable, elapsed)
        notify = self._finish_warm(job, fingerprint, executable, elapsed, reused)
        if self.metrics is not None and not reused:
            self.metrics.observe(
                SECONDS_METRIC, elapsed, experiment=job.experiment
            )
        if span is not None:
            tracer.end_span(
                span, outcome="warm", fingerprint=fingerprint,
                reusedTwin=reused, compileSeconds=round(elapsed, 4),
            )
        elif tracer is not None and getattr(tracer, "enabled", False):
            # the compile started before any trial requested this group
            # (admission prewarm); if a trial adopted the entry meanwhile,
            # record the measured interval into its trace retroactively
            with self._lock:
                e2 = self._by_key.get(job.key)
                trace_ctx = e2.trace if e2 is not None else None
            if trace_ctx:
                tracer.record_span(
                    "compile_service", job.experiment, trace_ctx[0],
                    trace_ctx[1], start=started, end=time.time(),
                    target=job.target, outcome="warm", fingerprint=fingerprint,
                    reusedTwin=reused, compileSeconds=round(elapsed, 4),
                )
        self._persist()
        if notify:
            self._notify(job.key)

    def _compile_probe(self, job: _Job) -> Tuple[str, Any, bool]:
        """Build the probe and AOT-compile it. One ``.trace`` yields both
        the canonical jaxpr (fingerprint — byte-identical to the analysis
        fingerprint) and the lowering; when an equal fingerprint is already
        warm the twin's executable is reused and ``.compile()`` is skipped.
        Runs on the inner (timeout-bounded) thread."""
        self._ensure_persistent_cache()
        import jax

        from ..analysis import program as semantic

        probe = job.builder(dict(job.assignments))
        jitted = jax.jit(probe.fn, donate_argnums=probe.donate_argnums)
        with self._lock:
            self.trace_counter += 1
        try:
            traced = jitted.trace(*probe.args)
            closed = traced.jaxpr
            lower = traced.lower
        except AttributeError:  # older jax without jit(...).trace
            closed = semantic.trace_probe(probe)
            lower = lambda: jitted.lower(*probe.args)  # noqa: E731
        fingerprint = semantic.fingerprint_jaxpr(closed, probe)
        with self._lock:
            twin = self._by_fingerprint.get(fingerprint)
            if (
                twin is not None
                and twin.state == STATE_WARM
                and twin.executable is not None
            ):
                return fingerprint, twin.executable, True
        cached = _process_cache_get(fingerprint)
        if cached is not None:
            # another service instance in this process (repeat experiment,
            # second controller) already compiled this exact program
            return fingerprint, cached[0], True
        executable = lower().compile()
        with self._lock:
            self.compiled_total += 1
        return fingerprint, executable, False

    def _ensure_persistent_cache(self) -> None:
        """Point this process at the shared persistent XLA cache before the
        first AOT compile, so subprocess/gang trials (which share the cache
        dir) find the service's compiles warm. Accelerator platforms only —
        same guard as the executors."""
        with self._lock:
            if self._cache_enabled:
                return
            self._cache_enabled = True
        try:
            from ..utils.compilation import enable_compilation_cache

            enable_compilation_cache()
        except Exception:
            pass

    def _finish_warm(
        self, job: _Job, fingerprint: str, executable, elapsed: float, reused: bool
    ) -> bool:
        with self._lock:
            entry = self._by_key.get(job.key)
            if entry is None:
                return False
            entry.state = STATE_WARM
            entry.fingerprint = fingerprint
            entry.compiled_at = time.time()
            entry.compile_seconds = round(elapsed, 4)
            entry.executable = executable
            self._by_fingerprint.setdefault(fingerprint, entry)
            self._warm_order.append(fingerprint)
            self._evict_executables_locked()
        return True

    def _evict_executables_locked(self) -> None:
        """Drop the oldest resident executables beyond the cap (metadata
        stays; those groups still hit the persistent XLA cache). Caller
        holds the service lock."""
        while len(self._warm_order) > self.MAX_RESIDENT_EXECUTABLES:
            old_fp = self._warm_order.pop(0)
            old = self._by_fingerprint.get(old_fp)
            if old is not None:
                old.executable = None

    def _fail(self, job: _Job, error: str) -> None:
        """Quarantine one group's fingerprint: exactly one CompileFailed
        event, never re-enqueued (request() finds the failed entry and
        leaves it alone) — trials fall back to inline compilation."""
        with self._lock:
            entry = self._by_key.get(job.key)
            if entry is None or entry.state == STATE_FAILED:
                return
            entry.state = STATE_FAILED
            entry.error = error.strip().splitlines()[-1][-400:] if error else "?"
        log.warning(
            "AOT compile of %s failed; fingerprint group quarantined "
            "(trials compile inline): %s", job.target, entry.error,
        )
        if self.metrics is not None:
            self.metrics.inc(FAILED_METRIC, experiment=job.experiment)
        if self.events is not None:
            self.events.event(
                job.experiment, "Experiment", job.experiment, "CompileFailed",
                f"AOT compile of {job.target} failed; group quarantined, "
                f"trials fall back to inline compilation: {entry.error}",
                warning=True,
            )
        self._persist()
        self._notify(job.key)

    def _notify(self, key) -> None:
        """Fire the state-transition listeners with NO service lock held —
        a listener re-entering the scheduler must not create a
        service→scheduler lock edge."""
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(key)
            except Exception:
                log.debug("compile listener failed", exc_info=True)

    # -- observability -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "compiled": self.compiled_total,
                "traces": self.trace_counter,
                "hits": self.hits,
                "misses": self.misses,
                "entries": len(self._by_key),
                "queueDepth": self._queue.qsize(),
            }

    def registry_snapshot(self) -> Dict[str, Any]:
        """The ``/api/compile`` + ``katib-tpu compile`` view; also what is
        persisted under ``<root>/compilesvc/registry.json``."""
        with self._lock:
            entries = [e.to_dict() for e in self._by_key.values()]
            stats = {
                "compiled": self.compiled_total,
                "traces": self.trace_counter,
                "hits": self.hits,
                "misses": self.misses,
                "queueDepth": self._queue.qsize(),
            }
        entries.sort(key=lambda e: e["submittedAt"])
        return {"entries": entries, **stats}

    def _persist(self) -> None:
        """Atomic JSON snapshot of the registry so ``katib-tpu compile``
        works offline after the controller exits. Best-effort: persistence
        failure never fails a compile."""
        if not self.persist_dir:
            return
        try:
            snapshot = self.registry_snapshot()
            os.makedirs(self.persist_dir, exist_ok=True)
            path = os.path.join(self.persist_dir, REGISTRY_FILE)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except Exception:
            log.debug("compile registry persist failed", exc_info=True)


def load_persisted_registry(persist_dir: str) -> Optional[Dict[str, Any]]:
    """Offline registry view for the CLI (`katib-tpu compile` without
    --url): the JSON snapshot the service wrote on its last transition."""
    path = os.path.join(persist_dir, REGISTRY_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
