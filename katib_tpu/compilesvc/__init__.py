"""AOT compile service — compilation as a scheduled, cached resource.

ROADMAP item 1 (ISSUE 8): BENCH_r02/r04 measured the e2e as
compile-dominated (23–51s XLA compile vs ~2ms steps), and HPO is the
pathological case — hundreds of trials differing only in runtime scalars.
This package moves that cost off the dispatch path: a controller-owned
:class:`~katib_tpu.compilesvc.service.CompileService` AOT-compiles each
dispatch group's canonical program (the PR 7 ``ProgramProbe``) on a small
worker pool and keeps a fingerprint-keyed executable registry the
scheduler, pack formation and the runtime context consult as dict hits.
"""

from .service import (
    STATE_COMPILING,
    STATE_FAILED,
    STATE_PENDING,
    STATE_WARM,
    CompileService,
    WarmProgram,
)

__all__ = [
    "CompileService",
    "WarmProgram",
    "STATE_PENDING",
    "STATE_COMPILING",
    "STATE_WARM",
    "STATE_FAILED",
]
